"""Native (C++) runtime components, loaded via ctypes.

The reference has no native code of its own (SURVEY.md §2: all native
execution lives in the torch/DGL wheels), so this layer is a
capability superset: the host-side ragged->dense packer that feeds the
TPU, the fused pad-and-cast variant the bf16 serving path dispatches
through, and the batched unpad/scatter that hands each response its
own rows in one call. Built on first import with g++ (cached as a .so
next to the source); every entry point has a pure-numpy fallback so
the framework works with no toolchain — and the fallbacks are
BIT-EXACT (tests/test_native.py pins it), so which implementation ran
never changes an answer, only its cost.

Whether the .so actually loaded is observable: :func:`status` is the
one probe; serving emits it as the one-time ``native_packer`` event
and records it in ``run.json`` so committed bench artifacts are
attributable to the path that produced them.

The ctypes signatures below are cross-checked against the C symbol
declarations in ``ragged_pack.cpp`` by graftlint rule GL007 (arity +
dtype tags) on every lint run — the .so cannot drift from its Python
caller silently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "ragged_pack.cpp")
_SO = os.path.join(_HERE, "_ragged_pack.so")

_lock = threading.Lock()
_lib = None
_lib_gil = None
_load_failed = False
_load_error: str | None = None

#: Payloads under this run through the GIL-HOLDING handle (PyDLL): a
#: sub-millisecond memory sweep must not pay a GIL release/reacquire
#: round-trip — under a live serve storm the reacquisition contends
#: with the submitting client thread and costs more than the sweep
#: (measured; docs/performance.md round 12). Above it (the threaded
#: multi-MB train-collate regime) the CDLL handle releases the GIL so
#: a long pack never stalls the interpreter.
GIL_HOLD_MAX_BYTES = 2 << 20


def _bf16():
    """numpy's bfloat16 via ml_dtypes (a jax dependency, not a new
    one); imported lazily so the packer has no import-time cost."""
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _bind(lib):
    """Attach the ctypes signatures to one dlopen handle. GL007
    cross-checks these against ragged_pack.cpp's extern "C"
    declarations (arity + dtype tags) on every lint run."""
    lib.gnot_pack_rows.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.gnot_pack_rows.restype = None
    lib.gnot_pack_rows_bf16.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.gnot_pack_rows_bf16.restype = None
    lib.gnot_unpad_rows.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.gnot_unpad_rows.restype = None
    return lib


def _load():
    """Build (if stale) and dlopen the packer; returns None on failure."""
    global _lib, _lib_gil, _load_failed, _load_error
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
                _SRC
            ):
                # Per-process tmp name: concurrent first-builds must not
                # interleave writes; os.replace stays atomic.
                tmp = f"{_SO}.{os.getpid()}.tmp"
                # -march=native is safe BY CONSTRUCTION: the .so is
                # built on first import on the machine that runs it
                # (never shipped), and it is what lets -O3 vectorize
                # the bf16 conversion sweep.
                # -fno-strict-aliasing: the bf16 sweep reads float bits
                # through a uint32 pointer (the form -O3 vectorizes).
                cmd = ["g++", "-O3", "-march=native", "-fno-strict-aliasing",
                       "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
                try:
                    subprocess.run(cmd, check=True, capture_output=True)
                except subprocess.CalledProcessError:
                    # Exotic toolchains may lack -march=native; the
                    # portable build is still correct, just slower.
                    cmd.remove("-march=native")
                    subprocess.run(cmd, check=True, capture_output=True)
                os.replace(tmp, _SO)
            # Two handles on one .so: CDLL releases the GIL per call
            # (long threaded packs), PyDLL holds it (sub-ms serve-sized
            # sweeps — see GIL_HOLD_MAX_BYTES).
            _lib_gil = _bind(ctypes.PyDLL(_SO))
            _lib = _bind(ctypes.CDLL(_SO))
        except (OSError, subprocess.CalledProcessError, AttributeError) as err:
            _load_failed = True
            _load_error = f"{type(err).__name__}: {err}"
    return _lib


def _handle(payload_bytes: int):
    """The dlopen handle for one call: GIL-holding under the payload
    bar, GIL-releasing above it. ``_load()`` must have succeeded."""
    return _lib_gil if payload_bytes < GIL_HOLD_MAX_BYTES else _lib


def native_available() -> bool:
    return _load() is not None


def status() -> dict:
    """One attributability record: which packer implementation this
    process runs (and why, when it fell back). Emitted as the
    ``native_packer`` event by serving and stamped into ``run.json`` —
    a bench artifact produced on the Python fallback must say so.
    ``impl: "native"`` means the .so loaded AND dispatch is the
    payload-gated ADAPTIVE policy — the thresholds are part of the
    record, so a reader can tell which payload classes actually ran
    the C sweep (below the bars the numpy fallback is the chosen fast
    path, by measurement, not by accident)."""
    lib = _load()
    return {
        "available": lib is not None,
        "impl": "native" if lib is not None else "python",
        "so": _SO if lib is not None else None,
        "error": _load_error,
        "pack_native_min_bytes": dict(PACK_NATIVE_MIN_BYTES),
        "unpad_native_min_bytes": NATIVE_UNPAD_MIN_BYTES,
    }


def pack_rows_numpy(
    arrs: list[np.ndarray], max_len: int, dtype: str = "float32"
) -> tuple[np.ndarray, np.ndarray]:
    """Fallback: pad [len_i, dim] float32 blocks to [n, max_len, dim] +
    [n, max_len] mask (zero pad at the row tail, reference utils.py:3-4).
    ``dtype="bfloat16"`` emits both in bfloat16 (ml_dtypes RNE cast —
    bitwise what the fused native sweep produces)."""
    target = _bf16() if dtype == "bfloat16" else np.dtype(np.float32)
    n, dim = len(arrs), arrs[0].shape[1]
    out = np.zeros((n, max_len, dim), target)
    mask = np.zeros((n, max_len), target)
    for i, a in enumerate(arrs):
        # Casting assignment: numpy/ml_dtypes converts in ONE pass (no
        # full-width temp), the same RNE the fused native sweep does.
        # Non-f32 input is normalized to f32 FIRST — the native path
        # always reads f32 bits, so a wider input must round f64->f32
        # ->bf16 on both paths or the bit-exactness contract breaks on
        # double-rounding edge values.
        out[i, : a.shape[0]] = np.ascontiguousarray(a, np.float32)
        mask[i, : a.shape[0]] = 1.0
    return out, mask


#: Minimum total payload (bytes of ragged f32 input) at which the
#: native sweep beats the numpy fallback, PER DTYPE — measured on this
#: box, not guessed (docs/performance.md "Low-precision serving",
#: round 12). bf16: the fused pad-and-cast wins 1.2-1.9x from ~100 KB
#: up (one vectorized pass vs numpy's cast-assign loop). f32: numpy's
#: calloc + per-sample C-core memcpy is already optimal — the ctypes
#: hop only pays once the 32 MB threading threshold makes the copy
#: itself parallel. Below the bar the fallback IS the fast path;
#: bitwise-identical either way (tests/test_native.py).
PACK_NATIVE_MIN_BYTES = {"bfloat16": 96 << 10, "float32": 32 << 20}


def pack_rows(
    arrs: list[np.ndarray], max_len: int, dtype: str = "float32"
) -> tuple[np.ndarray, np.ndarray]:
    """Pack ragged float32 row-blocks into a padded batch + mask, using
    the C++ packer where it measurably pays (``PACK_NATIVE_MIN_BYTES``).
    ``dtype="bfloat16"`` is the FUSED pad-and-cast path: one native
    sweep emits the half-width batch the bf16 serving program consumes
    (no full-width intermediate, no second pass)."""
    if dtype not in ("float32", "bfloat16"):
        raise ValueError(f"pack_rows dtype must be float32|bfloat16, got {dtype!r}")
    dim = arrs[0].shape[1] if arrs[0].ndim == 2 else -1
    for a in arrs:
        if a.ndim != 2 or a.shape[1] != dim:
            raise ValueError(
                f"pack_rows needs uniform [len_i, {dim}] blocks, got {a.shape}"
            )
    too_long = max(a.shape[0] for a in arrs)
    if too_long > max_len:
        raise ValueError(f"row block of {too_long} rows exceeds max_len={max_len}")
    lib = _load()
    payload = sum(a.shape[0] for a in arrs) * dim * 4
    if lib is None or payload < PACK_NATIVE_MIN_BYTES[dtype]:
        return pack_rows_numpy(arrs, max_len, dtype)
    n, dim = len(arrs), arrs[0].shape[1]
    contig = [np.ascontiguousarray(a, np.float32) for a in arrs]
    target = _bf16() if dtype == "bfloat16" else np.dtype(np.float32)
    # np.zeros, NOT np.empty: the C side writes payload + mask prefix
    # only (caller contract in ragged_pack.cpp) — calloc's lazy zero
    # pages make the pad tail free instead of a second full-width
    # memset sweep.
    out = np.zeros((n, max_len, dim), target)
    mask = np.zeros((n, max_len), target)
    # Pointer/length marshalling through two small numpy buffers: one
    # C-call's worth of setup, no per-array ctypes object churn.
    srcs = np.fromiter(
        (a.__array_interface__["data"][0] for a in contig),
        dtype=np.uintp, count=n,
    )
    lens = np.fromiter(
        (a.shape[0] for a in contig), dtype=np.int64, count=n
    )
    lib = _handle(payload)
    fn = lib.gnot_pack_rows_bf16 if dtype == "bfloat16" else lib.gnot_pack_rows
    fn(
        srcs.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        dim,
        max_len,
        out.ctypes.data_as(ctypes.c_void_p),
        mask.ctypes.data_as(ctypes.c_void_p),
    )
    return out, mask


#: Below this total payload the batched native unpad cannot amortize
#: its ctypes marshalling (~10-25 us/call measured) against numpy's
#: per-span C-core copies; above it the single native call (and, past
#: 32 MB, its threading) wins. Measured crossover on this box —
#: docs/performance.md "Low-precision serving" round 12.
NATIVE_UNPAD_MIN_BYTES = 4 << 20


def unpad_rows_numpy(
    out: np.ndarray, spans: list[tuple[int, int, int]]
) -> list[np.ndarray]:
    """Fallback: per-span OWNED copies ``out[row, off:off+length]`` —
    byte-identical to the native scatter (same bytes, same order),
    just one numpy copy per span instead of one batched call."""
    return [out[r, off : off + length].copy() for r, off, length in spans]


def unpad_rows(
    out: np.ndarray, spans: list[tuple[int, int, int]]
) -> list[np.ndarray]:
    """Batched unpad/scatter: slice each request's ``[length, dim]``
    block out of a dense ``[R, L, dim]`` dispatch output as an OWNED
    array (``spans`` are ``(row, offset, length)`` — the padded path
    uses ``(i, 0, n_i)``, the packed path its segment placements).
    Owned copies — not views — so a response never pins the whole
    dispatch buffer. Implementation is chosen where it pays: the numpy
    copy loop under ``NATIVE_UNPAD_MIN_BYTES`` (ctypes setup would
    dominate), ONE native call above it; both produce identical
    bytes."""
    if out.ndim != 3:
        raise ValueError(f"unpad_rows needs a [R, L, dim] output, got {out.shape}")
    n = len(spans)
    row_len, dim = out.shape[1], out.shape[2]
    for r, off, length in spans:
        if not (0 <= r < out.shape[0] and 0 <= off and off + length <= row_len):
            raise ValueError(
                f"span {(r, off, length)} out of bounds for {out.shape}"
            )
    total = sum(length for _, _, length in spans) * dim * out.itemsize
    lib = _load()
    if lib is None or n == 0 or total < NATIVE_UNPAD_MIN_BYTES:
        return unpad_rows_numpy(out, spans)
    src = np.ascontiguousarray(out)
    tok_bytes = dim * src.itemsize
    dsts = [np.empty((length, dim), src.dtype) for _, _, length in spans]
    meta = np.empty((3, n), np.int64)
    meta[0] = [s[0] for s in spans]
    meta[1] = [s[1] for s in spans]
    meta[2] = [s[2] for s in spans]
    ptrs = np.fromiter(
        (d.__array_interface__["data"][0] for d in dsts),
        dtype=np.uintp, count=n,
    )
    as_i64 = ctypes.POINTER(ctypes.c_int64)
    lib = _handle(total)
    lib.gnot_unpad_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        meta[0].ctypes.data_as(as_i64),
        meta[1].ctypes.data_as(as_i64),
        meta[2].ctypes.data_as(as_i64),
        n,
        row_len * tok_bytes,
        tok_bytes,
        ptrs.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
    )
    return dsts
