// Native ragged->dense batch packer: the host-side hot loop of the data
// pipeline and the serving dispatch path.
//
// The reference pads ragged meshes in Python inside the train loop
// (/root/reference/main.py:63-82, utils.py:3-4): one torch op per sample
// per field. The numpy fallback in gnot_tpu/data/batch.py is the same
// shape of work. This packer does the whole batch in one call: a single
// pass of memcpy per sample row-block, zero-fill for the pad tail, and
// the 0/1 mask written in the same sweep — no per-sample allocations, no
// interpreter in the loop. Threaded over samples for large batches.
//
// Serving additions (round 12, trace_report-indicted host phases):
//
// * gnot_pack_rows_bf16 — FUSED pad-and-cast: the same sweep, emitting
//   bfloat16 (round-to-nearest-even, Eigen/ml_dtypes-identical) so a
//   bf16 serving dispatch assembles its half-width batch in one pass
//   instead of pack-then-astype (two passes, an interpreter hop, and a
//   full-width intermediate).
// * gnot_unpad_rows — batched unpad/scatter: every response's
//   [n_i, out] rows copied out of the dispatch output in ONE native
//   call (padded rows or packed (row, offset) segments alike) instead
//   of a Python loop of slice-copies.
//
// ABI: plain C symbols loaded via ctypes (no pybind11 dependency).
// tools/lint.py rule GL007 cross-checks these signatures against the
// ctypes bindings in __init__.py (arity + dtype tags) on every run.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// The bf16 conversion inside gnot_pack_rows_bf16 is EXACTLY the
// Eigen::bfloat16 round-to-nearest-even ml_dtypes uses, so the Python
// fallback (numpy astype via ml_dtypes) is bitwise-identical — the
// parity tests assert it, NaNs included.

// Run pack_one(i) for i in [0, n), threaded only when the payload is
// so large that thread spawn (hundreds of us on a busy host) is noise.
// Measured on this class of box: per-dispatch serve payloads (KBs to a
// few MB) lose to spawn cost every time — memcpy at >10 GB/s finishes
// before the second thread starts — so the bar is 32 MB, not "a few".
template <typename F>
void for_samples(int64_t n, int64_t total_bytes, F&& pack_one) {
  const unsigned hw = std::thread::hardware_concurrency();
  if (total_bytes < (int64_t{32} << 20) || hw <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) pack_one(i);
    return;
  }
  const int64_t n_threads = std::min<int64_t>(n, hw);
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  for (int64_t t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t] {
      for (int64_t i = t; i < n; i += n_threads) pack_one(i);
    });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Pack n ragged [len_i, dim] float32 row-blocks into a dense
// [n, max_len, dim] tensor and a [n, max_len] 0/1 mask. `srcs[i]`
// points at sample i's contiguous data.
//
// CALLER CONTRACT: `out` and `mask` arrive ZERO-INITIALIZED (the
// Python side allocates them with np.zeros — calloc-backed lazy zero
// pages). Only the payload and the mask's 1-prefix are written here;
// the pad tail is never touched, so untouched pad PAGES are never
// faulted in. This is the difference between beating numpy's own
// calloc+copy path and losing to it by the width of a redundant
// memset (measured on this box; docs/performance.md round 12).
void gnot_pack_rows(const float** srcs, const int64_t* lens, int64_t n,
                    int64_t dim, int64_t max_len, float* out, float* mask) {
  const int64_t row_bytes = dim * static_cast<int64_t>(sizeof(float));
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lens[i] * row_bytes;
  for_samples(n, total, [&](int64_t i) {
    const int64_t len = lens[i];
    std::memcpy(out + i * max_len * dim, srcs[i],
                static_cast<size_t>(len * row_bytes));
    float* m = mask + i * max_len;
    for (int64_t r = 0; r < len; ++r) m[r] = 1.0f;
  });
}

// Fused pad-and-cast: gnot_pack_rows semantics (same zero-initialized
// caller contract), but the output tensor and mask are bfloat16
// (uint16 bits, RNE) — ONE sweep builds the half-width dispatch batch
// a bf16 serving program consumes, no full-width intermediate, no
// second pass. The cast loop reads the float bits through a uint32
// pointer (built with -fno-strict-aliasing) and keeps the NaN fixup
// as a branchless select so -O3 -march=native vectorizes it.
void gnot_pack_rows_bf16(const float** srcs, const int64_t* lens, int64_t n,
                         int64_t dim, int64_t max_len, uint16_t* out,
                         uint16_t* mask) {
  const int64_t row_bytes = dim * static_cast<int64_t>(sizeof(float));
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lens[i] * row_bytes;
  for_samples(n, total, [&](int64_t i) {
    const int64_t len = lens[i];
    const uint32_t* src = reinterpret_cast<const uint32_t*>(srcs[i]);
    uint16_t* dst = out + i * max_len * dim;
    const int64_t elems = len * dim;
    // Mask-select form (not value ternaries): gcc 10 refuses to
    // vectorize mixed-width conditional moves but turns this into
    // 64-byte AVX-512 vectors (measured 4x; -fopt-info-vec verified).
    for (int64_t e = 0; e < elems; ++e) {
      const uint32_t x = src[e];
      const uint32_t lsb = (x >> 16) & 1u;
      const uint32_t rne = (x + 0x7FFFu + lsb) >> 16;
      const uint32_t nan_bits = (x >> 31) ? 0xFFC0u : 0x7FC0u;
      const uint32_t is_nan =
          (x & 0x7FFFFFFFu) > 0x7F800000u ? 0xFFFFFFFFu : 0u;
      dst[e] = static_cast<uint16_t>((is_nan & nan_bits) | (~is_nan & rne));
    }
    uint16_t* m = mask + i * max_len;
    for (int64_t r = 0; r < len; ++r) m[r] = 0x3F80u;  // 1.0 in bfloat16
  });
}

// Batched unpad/scatter: copy each sample's [len_i, dim] block out of a
// dense [R, row_len, dim] dispatch output into its own destination
// buffer, in one call. Byte-oriented so any element dtype works:
// sample i's block starts at src + rows[i]*row_bytes + offs[i]*tok_bytes
// and spans lens[i]*tok_bytes (tok_bytes = dim * itemsize). Covers the
// padded path (rows=i, offs=0) and the packed path ((row, offset)
// segment placements) with the same symbol.
void gnot_unpad_rows(const char* src, const int64_t* rows,
                     const int64_t* offs, const int64_t* lens, int64_t n,
                     int64_t row_bytes, int64_t tok_bytes, char** dsts) {
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lens[i] * tok_bytes;
  for_samples(n, total, [&](int64_t i) {
    std::memcpy(dsts[i], src + rows[i] * row_bytes + offs[i] * tok_bytes,
                static_cast<size_t>(lens[i] * tok_bytes));
  });
}

}  // extern "C"
