"""GL006 — aliased-host-view: use-after-donate through a host alias.

The nine-times-root-caused bug shape of this repo's history (PR 6/7/10
parity failures, the PR 2 checkpoint corruption): on CPU,
``jax.device_get`` / ``np.asarray`` over a device value return
**zero-copy NumPy views** of the live device buffers. Snapshot such a
view, run a donating dispatch on the aliased state, and the "snapshot"
silently advances (or turns to garbage) — surfacing as ~1e-3 parity
drift three layers from the actual bug:

.. code-block:: python

    host = jax.device_get(state.params)   # zero-copy view
    state, loss = train_step(state, b, lr)  # donates state's buffers
    np.testing.assert_allclose(host, ...)   # GL006: stale host view

The rule runs an intra-function, source-order dataflow pass:

* **alias seeding** — an assignment whose RHS is ``jax.device_get(X)``,
  ``np.asarray(X)`` / ``jnp.asarray(X)``, or a view-preserving
  ``jax.tree.map`` over either, links the target name to the source
  expression key ``X`` (chains and name-to-name propagation included).
  Copying forms (``np.array``, ``np.copy``, ``copy.deepcopy``,
  ``jax.tree.map(np.array, ...)``) break the chain — they are the fix.
* **donation** — any statement invoking a donating callable (resolved
  via ``core.donors_for_file``: configured names, intra-file jit
  donors, the project call graph's wrapper/factory donors, and
  self-attribute donors like ``Trainer.fit`` donating ``self.state``)
  on a source related to a live alias poisons that alias.
* **stale read** — the first later read of a poisoned alias is the
  finding, at the read's own line.

Rebinding an alias clears it; rebinding the *source* before the
donation breaks the link (the view points at the old buffers, which
the donating call never touches). Reads inside the donating statement
itself (the call's own arguments) are evaluated before the donation
and stay clean.
"""

from __future__ import annotations

import ast

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    donated_keys_of_call,
    donors_for_file,
    dotted_name,
    full_key,
    keys_related,
    register,
    terminal_name,
)

#: Callable terminal names that COPY their input — assignments through
#: these break the alias chain (they are exactly the committed fixes).
_COPY_FNS = ("array", "copy", "deepcopy")

#: numpy-ish module heads whose ``asarray`` is view-preserving.
_NP_HEADS = ("np", "numpy", "jnp", "jax.numpy")


def _is_np_asarray(call: ast.Call) -> bool:
    if terminal_name(call.func) != "asarray":
        return False
    if not isinstance(call.func, ast.Attribute):
        return False
    return dotted_name(call.func.value) in _NP_HEADS


def _alias_source(node: ast.AST, alias: dict[str, str]) -> str | None:
    """Device-expression key ``node`` evaluates to a host VIEW of, or
    None when it is a copy / untracked value. ``alias`` resolves names
    that are themselves host views back to their device source."""
    if not isinstance(node, ast.Call):
        return None
    fname = terminal_name(node.func)
    if fname == "device_get" and node.args:
        return _source_or_key(node.args[0], alias)
    if _is_np_asarray(node) and node.args:
        return _source_or_key(node.args[0], alias)
    if fname == "map" and "tree" in dotted_name(node.func):
        # jax.tree.map(f, X): aliasing only for a PROVABLY
        # view-preserving f (`asarray`). Anything else — np.array,
        # copying lambdas, arbitrary transforms — is assumed to copy:
        # the rule must hold zero false positives over the clean tree,
        # and the committed fixes are exactly the copying maps.
        if len(node.args) >= 2 and terminal_name(node.args[0]) == "asarray":
            return _source_or_key(node.args[1], alias)
    return None


def _source_or_key(node: ast.AST, alias: dict[str, str]) -> str | None:
    src = _alias_source(node, alias)
    if src is not None:
        return src
    key = full_key(node)
    if key is None:
        return None
    # A name that is itself a host view aliases that view's source.
    return alias.get(key, key)


def _scope_statements(scope: ast.AST) -> list[ast.stmt]:
    """Statements of one scope in source order, without descending into
    nested function/class bodies (those are their own scopes)."""
    out: list[ast.stmt] = []

    def visit(body):
        for stmt in body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            out.append(stmt)
            for field in ("body", "orelse", "finalbody"):
                visit(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                visit(handler.body)
            for case in getattr(stmt, "cases", []) or []:
                visit(case.body)  # match arms (ast.Match)

    body = scope.body if isinstance(scope.body, list) else [scope.body]
    visit(body)
    out.sort(key=lambda s: (s.lineno, s.col_offset))
    return out


def _shallow_nodes(stmt: ast.stmt):
    """Nodes of ``stmt`` without nested def/lambda bodies (their reads
    execute later, in their own scope)."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def _assigned_entries(stmt: ast.stmt) -> list[tuple[str, ast.AST | None]]:
    """(target key, RHS expr or None) pairs this statement binds. The
    RHS is only attached for the single-target ``name = value`` shape —
    tuple unpacking and loop targets just clear their keys."""
    if isinstance(stmt, ast.Assign):
        if len(stmt.targets) == 1 and full_key(stmt.targets[0]) is not None:
            return [(full_key(stmt.targets[0]), stmt.value)]
        out = []
        for t in stmt.targets:
            for node in ast.walk(t):
                key = full_key(node)
                if key is not None and isinstance(
                    node.ctx, ast.Store
                ):
                    out.append((key, None))
        return out
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        key = full_key(stmt.target)
        value = stmt.value if isinstance(stmt, ast.AnnAssign) else None
        return [(key, value)] if key else []
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [
            (full_key(n), None)
            for n in ast.walk(stmt.target)
            if full_key(n) is not None
        ]
    if isinstance(stmt, ast.With):
        return [
            (full_key(i.optional_vars), None)
            for i in stmt.items
            if i.optional_vars is not None
            and full_key(i.optional_vars) is not None
        ]
    if isinstance(stmt, ast.Delete):
        return [
            (full_key(t), None)
            for t in stmt.targets
            if full_key(t) is not None
        ]
    return []


@register
class AliasedHostView(Rule):
    id = "GL006"
    title = "aliased-host-view"
    hint = (
        "copy the host snapshot by value before the donating call "
        "(`jax.tree.map(np.array, jax.device_get(x))`, or `np.array(x)` "
        "for one array) — a zero-copy view of donated buffers is "
        "undefined after the dispatch"
    )

    def check_file(self, ctx: FileContext) -> list[Finding]:
        donors = donors_for_file(ctx)
        findings: list[Finding] = []
        scopes: list[ast.AST] = [ctx.tree]
        scopes += [
            n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            findings.extend(self._check_scope(ctx, scope, donors))
        return findings

    def _check_scope(
        self, ctx: FileContext, scope: ast.AST, donors
    ) -> list[Finding]:
        findings: list[Finding] = []
        alias: dict[str, str] = {}  # view name -> device source key
        poisoned: dict[str, dict] = {}  # view name -> donation info
        for stmt in _scope_statements(scope):
            # (a) reads of already-poisoned views — the finding, at the
            # read's own line (first read per view).
            for node in _shallow_nodes(stmt):
                if not isinstance(node, (ast.Name, ast.Attribute)):
                    continue
                if not isinstance(node.ctx, ast.Load):
                    continue
                key = full_key(node)
                info = poisoned.get(key) if key else None
                if info is None:
                    continue
                findings.append(
                    Finding(
                        rule=self.id,
                        path=ctx.path,
                        line=node.lineno,
                        message=(
                            f"`{key}` is a host view of `{info['source']}`"
                            f", whose buffers were donated to "
                            f"`{info['donor']}(...)` at line "
                            f"{info['line']}; the view is stale"
                        ),
                        hint=self.hint,
                    )
                )
                poisoned.pop(key, None)
                alias.pop(key, None)
            # (b) donations in this statement poison related aliases
            # (the statement's own argument reads happened before the
            # donation and stay clean by the (a)-before-(b) ordering).
            for node in _shallow_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                for dkey in donated_keys_of_call(node, donors):
                    for name, src in list(alias.items()):
                        if keys_related(src, dkey):
                            poisoned.setdefault(
                                name,
                                {
                                    "source": src,
                                    "donor": terminal_name(node.func),
                                    "line": node.lineno,
                                },
                            )
            # (c) bindings: seed new aliases, clear rebound ones, break
            # source links whose device value was replaced.
            for key, rhs in _assigned_entries(stmt):
                alias.pop(key, None)
                poisoned.pop(key, None)
                # Rebinding a SOURCE breaks its links: views of the old
                # value are untouched by donations of the new one.
                for name, src in list(alias.items()):
                    if keys_related(src, key):
                        alias.pop(name, None)
                if rhs is not None:
                    src = _alias_source(rhs, alias)
                    if src is None and full_key(rhs) is not None:
                        # name-to-name propagation: h2 = host
                        src = alias.get(full_key(rhs))
                    if src is not None:
                        alias[key] = src
        return findings
