"""GL007 — native ABI drift (ctypes bindings vs C symbol declarations).

The native packer (``gnot_tpu/native/ragged_pack.cpp``) is loaded via
ctypes with hand-written ``argtypes`` in ``gnot_tpu/native/__init__.py``.
Nothing type-checks that seam: add a parameter on one side only and the
call still "works" — reading garbage through a mis-laid stack, the
classic silent-drift bug shape for a .so behind a Python caller.

This rule parses BOTH sides on every lint run and compares, per
exported ``gnot_*`` symbol:

* the symbol exists on both sides (a binding without a C definition,
  or an ``extern "C"`` export nothing binds, are both findings);
* arity agrees;
* every parameter's dtype TAG agrees, under a coarse canonical map —
  pointer-to-pointer (``const float**``/``char**``) is
  ``POINTER(c_void_p)``, ``int64_t*`` is ``POINTER(c_int64)``, scalar
  ``int64_t`` is ``c_int64``, and any other single pointer
  (``float*``, ``uint16_t*``, ``char*``) is the opaque ``c_void_p``
  the bindings pass buffers as.

Project-level (the C++ file is not a lintable Python file): findings
bypass ``--changed`` diff scoping like GL005's, because an edit to
either file alone can cause them.
"""

from __future__ import annotations

import ast
import os
import re

from gnot_tpu.analysis.core import (
    Finding,
    ProjectContext,
    Rule,
    register,
)

#: C parameter type -> canonical ctypes tag. Checked after stripping
#: ``const``/whitespace and the parameter name. Unknown types map to
#: themselves, which can only ever MATCH nothing — an unknown type is
#: a (loud) mismatch, never a silent pass.
_C_TAGS = (
    (re.compile(r"^.*\*\s*\*$"), "POINTER(c_void_p)"),
    (re.compile(r"^u?int64_t\s*\*$"), "POINTER(c_int64)"),
    (re.compile(r"^u?int64_t$"), "c_int64"),
    (re.compile(r"^[A-Za-z_][A-Za-z_0-9]*\s*\*$"), "c_void_p"),
)

_DECL_RE = re.compile(
    r"\b(?:void|int|int64_t|float|double)\s+(gnot_\w+)\s*\(([^)]*)\)",
    re.DOTALL,
)


def _strip_c_comments(src: str) -> str:
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.DOTALL)
    return re.sub(r"//[^\n]*", " ", src)


def _c_param_tag(param: str) -> str:
    """Canonical tag of one C parameter declaration."""
    p = param.strip()
    # Drop the parameter NAME: the last identifier not glued to a '*'.
    p = re.sub(r"\b[A-Za-z_][A-Za-z_0-9]*\s*$", "", p).strip()
    p = re.sub(r"\bconst\b", "", p)
    p = re.sub(r"\s+", "", p)
    # Normalize '**' spacing forms like '* *'.
    for pat, tag in _C_TAGS:
        if pat.match(p):
            return tag
    return p or "?"


def _c_symbols(path: str) -> dict[str, tuple[int, list[str]]]:
    """``symbol -> (line, [tags])`` for every ``gnot_*`` declaration."""
    with open(path, encoding="utf-8") as f:
        raw = f.read()
    src = _strip_c_comments(raw)
    out: dict[str, tuple[int, list[str]]] = {}
    for m in _DECL_RE.finditer(src):
        name, args = m.group(1), m.group(2)
        line = src.count("\n", 0, m.start()) + 1
        params = [a for a in args.split(",") if a.strip()]
        out[name] = (line, [_c_param_tag(a) for a in params])
    return out


def _ctypes_tag(node: ast.AST) -> str:
    """Canonical tag of one ctypes argtypes element (AST form)."""

    def terminal(n: ast.AST) -> str:
        if isinstance(n, ast.Attribute):
            return n.attr
        if isinstance(n, ast.Name):
            return n.id
        return "?"

    if isinstance(node, ast.Call) and terminal(node.func) == "POINTER":
        inner = terminal(node.args[0]) if node.args else "?"
        return f"POINTER({inner})"
    return terminal(node)


def _py_bindings(path: str) -> dict[str, tuple[int, list[str]]]:
    """``symbol -> (line, [tags])`` from ``lib.<symbol>.argtypes = [...]``
    assignments anywhere in the bindings module."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out: dict[str, tuple[int, list[str]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (
            isinstance(t, ast.Attribute)
            and t.attr == "argtypes"
            and isinstance(t.value, ast.Attribute)
        ):
            continue
        symbol = t.value.attr
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            out[symbol] = (node.lineno, ["?unparseable"])
            continue
        out[symbol] = (
            node.lineno,
            [_ctypes_tag(e) for e in node.value.elts],
        )
    return out


@register
class NativeAbiDrift(Rule):
    id = "GL007"
    title = "native-abi-drift"
    hint = (
        "keep gnot_tpu/native/__init__.py argtypes and the extern \"C\" "
        "declarations in ragged_pack.cpp in lockstep (arity + dtype "
        "tags; see docs/static_analysis.md GL007 for the tag map)"
    )

    def check_project(self, project: ProjectContext) -> list[Finding]:
        cfg = project.config
        py_rel = cfg.native_binding
        cpp_rel = cfg.native_source
        py_path = os.path.join(project.root, py_rel)
        cpp_path = os.path.join(project.root, cpp_rel)
        if not (os.path.exists(py_path) and os.path.exists(cpp_path)):
            return []  # fixture sandboxes carry no native layer
        try:
            bindings = _py_bindings(py_path)
            symbols = _c_symbols(cpp_path)
        except (OSError, SyntaxError) as err:
            return [
                Finding(
                    rule=self.id,
                    path=py_rel,
                    line=1,
                    message=f"native ABI check could not parse: {err}",
                    hint=self.hint,
                )
            ]
        findings: list[Finding] = []
        for symbol, (line, py_tags) in sorted(bindings.items()):
            if symbol not in symbols:
                findings.append(
                    Finding(
                        rule=self.id,
                        path=py_rel,
                        line=line,
                        message=(
                            f"ctypes binds {symbol!r} but {cpp_rel} "
                            "declares no such extern \"C\" symbol"
                        ),
                        hint=self.hint,
                    )
                )
                continue
            c_line, c_tags = symbols[symbol]
            if len(py_tags) != len(c_tags):
                findings.append(
                    Finding(
                        rule=self.id,
                        path=py_rel,
                        line=line,
                        message=(
                            f"{symbol!r} arity drift: ctypes binds "
                            f"{len(py_tags)} argtypes, {cpp_rel}:{c_line} "
                            f"declares {len(c_tags)} parameters"
                        ),
                        hint=self.hint,
                    )
                )
                continue
            for i, (pt, ct) in enumerate(zip(py_tags, c_tags)):
                if pt != ct:
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=py_rel,
                            line=line,
                            message=(
                                f"{symbol!r} dtype-tag drift at arg {i}: "
                                f"ctypes {pt}, C declares {ct} "
                                f"({cpp_rel}:{c_line})"
                            ),
                            hint=self.hint,
                        )
                    )
        for symbol, (c_line, _) in sorted(symbols.items()):
            if symbol not in bindings:
                findings.append(
                    Finding(
                        rule=self.id,
                        path=cpp_rel,
                        line=c_line,
                        message=(
                            f"extern \"C\" symbol {symbol!r} has no "
                            f"ctypes binding in {py_rel} (dead export, "
                            "or a binding was forgotten)"
                        ),
                        hint=self.hint,
                    )
                )
        return findings
