"""GL008/GL009 — the concurrency plane: lock-order graph + blocking
calls under locks.

The serving stack holds ~25 distinct ``threading.Lock``s across
router/federation/autoscaler/metrics, and the cross-object call chains
(autoscale tick → router → server drain; cluster router → host agent →
wire link) take them in nested orders nobody checks by hand. Two bug
classes follow, both invisible to GL004's per-attribute discipline:

* **GL008 lock-order inversion** — thread A acquires ``X`` then ``Y``,
  thread B acquires ``Y`` then ``X``: a deadlock that only fires under
  the right interleaving. The rule resolves every ``with self._lock:``
  site to a per-class lock identity (``ClassName._lock``; module- and
  function-local locks get module-qualified identities), propagates
  held-lock sets through the intra-project call graph (``self.m()``,
  typed ``self.attr.m()`` receivers, project-unique method names —
  the same terminal-name philosophy as the donation graph), builds the
  directed *acquires-while-holding* graph, and reports every cycle
  with a ``file:line`` witness path for each edge. ``RLock``
  self-reentrancy is not a finding; re-acquiring a non-reentrant lock
  (directly or through a call chain) is reported as a self-deadlock.
* **GL009 blocking-call-under-lock** — a ``Future.result()``,
  ``Thread.join()``/``Event.wait()`` without timeout, socket
  ``recv``/``accept``, ``subprocess`` wait, or configured slow
  callable (engine ``infer*``/``warmup``, ``aot_compile``, checkpoint
  I/O — ``slow_callables`` in ``[tool.graftlint]``) lexically inside a
  held-lock region wedges every thread that wants the lock. Justified
  cases carry a ``#: allowed_blocking — reason`` annotation on (or
  immediately above) the call line; the reason is mandatory.

The call-graph machinery is shared with ``tools/lockmap_report.py``
via :func:`build_lock_graph`, which emits the committed
``docs/artifacts/lockmap.jsonl`` census. Resolution is deliberately
import-free and terminal-name keyed; an ambiguous method name (defined
by several classes, untyped receiver) resolves to *nothing* rather
than to every candidate — missed edges are honest, invented cycles are
not.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectContext,
    Rule,
    dotted_name,
    register,
    terminal_name,
)

_LOCK_CTORS = ("Lock", "RLock", "Condition")

#: Method names ubiquitous on builtin containers/IO/concurrency
#: objects. The project-unique-name fallback must not resolve these —
#: ``self._entries.get(key)`` is a dict read, not a call into the one
#: class that happens to define a ``get`` method.
_BUILTIN_METHODS = frozenset(
    {
        "get", "pop", "append", "extend", "add", "remove", "discard",
        "clear", "update", "items", "keys", "values", "copy",
        "setdefault", "popitem", "insert", "count", "index", "sort",
        "reverse", "join", "split", "strip", "format", "encode",
        "decode", "read", "write", "readline", "flush", "close",
        "put", "get_nowait", "put_nowait", "acquire", "release",
        "wait", "notify", "notify_all", "start", "send", "recv",
        "accept", "result", "done", "cancel", "set", "is_set",
    }
)

#: Constructors whose result is a builtin container — an attribute
#: assigned one of these has NO project-class methods; calls through
#: it must not resolve via the unique-name fallback.
_BUILTIN_CTORS = frozenset(
    {"dict", "list", "set", "tuple", "defaultdict", "OrderedDict", "deque",
     "Counter", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
)

#: Annotation contract for justified blocking calls: on the call line
#: or the line immediately above (which must start with "#:").
_ALLOWED_RE = re.compile(r"#:\s*allowed_blocking\b\s*(?:[—–-]+\s*)?(.*)")

#: Bound on interprocedural witness chains — deeper chains exist but a
#: six-hop path is already past what a reviewer will follow.
_CHAIN_CAP = 6
_FIXPOINT_ROUNDS = 12


def _lock_ctor_kind(node: ast.AST) -> str | None:
    """"Lock"/"RLock"/"Condition" when ``node`` constructs one
    (``threading.Lock()`` or bare ``Lock()``), else None."""
    if isinstance(node, ast.Call) and terminal_name(node.func) in _LOCK_CTORS:
        return terminal_name(node.func)
    return None


def _module_stem(rel_path: str) -> str:
    """Short module identity for lock naming: ``gnot_tpu/native/
    __init__.py`` -> "native", ``serve/federation.py`` -> "federation"."""
    parts = rel_path.replace(os.sep, "/").rsplit(".py", 1)[0].split("/")
    if parts and parts[-1] == "__init__":
        parts.pop()
    return parts[-1] if parts else rel_path


class _ClassInfo:
    """Per-class lock model: lock attributes (with constructor kind),
    attribute receiver types, and method defs."""

    __slots__ = ("name", "locks", "attr_types", "methods")

    def __init__(self, name: str):
        self.name = name
        self.locks: dict[str, tuple[str, int]] = {}  # attr -> (kind, line)
        self.attr_types: dict[str, str] = {}  # attr -> class name
        self.methods: dict[str, ast.AST] = {}


class _FileLockInfo:
    __slots__ = ("classes", "module_locks", "functions", "stem")

    def __init__(self, stem: str):
        self.stem = stem
        self.classes: dict[str, _ClassInfo] = {}
        self.module_locks: dict[str, tuple[str, int]] = {}
        self.functions: dict[str, ast.AST] = {}


def _file_lock_info(ctx: FileContext) -> _FileLockInfo:
    """Lock declarations in one file (memoized per FileContext)."""
    cached = getattr(ctx, "_lockinfo", None)
    if cached is not None:
        return cached
    info = _FileLockInfo(_module_stem(ctx.path))
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            kind = _lock_ctor_kind(node.value)
            if kind:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        info.module_locks[t.id] = (kind, node.lineno)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.functions[node.name] = node
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        ci = info.classes.setdefault(cls.name, _ClassInfo(cls.name))
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ci.methods.setdefault(fn.name, fn)
            # Annotated __init__ params give receiver types for
            # `self.router = router`-style wiring.
            param_types: dict[str, str] = {}
            for a in (*fn.args.posonlyargs, *fn.args.args):
                if a.annotation is not None:
                    tn = terminal_name(a.annotation)
                    if tn and tn[:1].isupper():
                        param_types[a.arg] = tn
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = _lock_ctor_kind(node.value)
                    if kind:
                        ci.locks.setdefault(t.attr, (kind, node.lineno))
                    elif isinstance(
                        node.value,
                        (ast.Dict, ast.List, ast.Set, ast.Tuple,
                         ast.DictComp, ast.ListComp, ast.SetComp),
                    ):
                        ci.attr_types.setdefault(t.attr, "<builtin>")
                    elif isinstance(node.value, ast.Call):
                        tn = terminal_name(node.value.func)
                        if tn in _BUILTIN_CTORS:
                            ci.attr_types.setdefault(t.attr, "<builtin>")
                        elif tn and tn[:1].isupper():
                            ci.attr_types.setdefault(t.attr, tn)
                    elif isinstance(node.value, ast.Name):
                        tn = param_types.get(node.value.id)
                        if tn:
                            ci.attr_types.setdefault(t.attr, tn)
    ctx._lockinfo = info
    return info


class _ProjectLocks:
    """Cross-file lock model: every lock identity, every method keyed
    ``(ClassName, method)``, and the unique-name resolution indexes."""

    def __init__(self) -> None:
        #: lock id -> {"kind", "file", "line", "module", "class"}
        self.nodes: dict[str, dict] = {}
        self.class_locks: dict[str, dict[str, tuple[str, str]]] = {}
        self.attr_types: dict[str, dict[str, str]] = {}
        self.methods: dict[tuple[str, str], tuple[FileContext, ast.AST, str]] = {}
        self.method_classes: dict[str, set[str]] = {}
        self.functions: dict[str, tuple[FileContext, ast.AST]] = {}
        self._dup_functions: set[str] = set()

    def add_file(self, ctx: FileContext) -> None:
        info = _file_lock_info(ctx)
        for name, (kind, line) in info.module_locks.items():
            lid = f"{info.stem}.{name}"
            self.nodes.setdefault(
                lid,
                {
                    "kind": kind,
                    "file": ctx.path,
                    "line": line,
                    "module": info.stem,
                    "class": None,
                },
            )
        for fname, fn in info.functions.items():
            if fname in self.functions or fname in self._dup_functions:
                self.functions.pop(fname, None)
                self._dup_functions.add(fname)
            else:
                self.functions[fname] = (ctx, fn)
        for cname, ci in info.classes.items():
            locks = self.class_locks.setdefault(cname, {})
            for attr, (kind, line) in ci.locks.items():
                lid = f"{cname}.{attr}"
                locks.setdefault(attr, (kind, lid))
                self.nodes.setdefault(
                    lid,
                    {
                        "kind": kind,
                        "file": ctx.path,
                        "line": line,
                        "module": info.stem,
                        "class": cname,
                    },
                )
            types = self.attr_types.setdefault(cname, {})
            for attr, tn in ci.attr_types.items():
                types.setdefault(attr, tn)
            for mname, fn in ci.methods.items():
                self.methods.setdefault((cname, mname), (ctx, fn, cname))
                self.method_classes.setdefault(mname, set()).add(cname)


@dataclasses.dataclass(frozen=True)
class _Held:
    lock: str
    kind: str
    line: int


class _Acq:
    """One lock acquisition with the locks lexically held at it."""

    __slots__ = ("lock", "kind", "line", "held")

    def __init__(self, lock: str, kind: str, line: int, held: tuple):
        self.lock, self.kind, self.line, self.held = lock, kind, line, held


class _CallSite:
    """One call expression inside a function body, with held locks and
    (when resolvable) the project callable it targets."""

    __slots__ = ("node", "key", "line", "held")

    def __init__(self, node: ast.Call, key, line: int, held: tuple):
        self.node, self.key, self.line, self.held = node, key, line, held


def _local_lock_aliases(
    fn: ast.AST, ci: _ClassInfo | None, info: _FileLockInfo
) -> tuple[
    dict[str, tuple[str, str]],
    dict[str, tuple[str, str]],
    dict[str, tuple[str, int]],
]:
    """``(aliases, local_locks, local_lines)``: single-assignment local
    names bound to a known lock (``wlock = self._wlock``),
    function-local lock constructions (``wlock = threading.Lock()``),
    and — keyed by lock identity — each construction's ``(kind, line)``
    so the graph can register these as nodes. A name assigned more
    than once is dropped — its identity is not trackable."""
    assigned: dict[str, int] = {}
    aliases: dict[str, tuple[str, str]] = {}
    local_locks: dict[str, tuple[str, str]] = {}
    local_lines: dict[str, tuple[str, int]] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            assigned[t.id] = assigned.get(t.id, 0) + 1
            kind = _lock_ctor_kind(node.value)
            if kind:
                lid = f"{info.stem}.{getattr(fn, 'name', '<fn>')}.{t.id}"
                local_locks[t.id] = (kind, lid)
                local_lines[lid] = (kind, node.lineno)
            elif (
                ci is not None
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
                and node.value.attr in ci.locks
            ):
                aliases[t.id] = (
                    ci.locks[node.value.attr][0],
                    f"{ci.name}.{node.value.attr}",
                )
    for name, n in assigned.items():
        if n > 1:
            aliases.pop(name, None)
            dropped = local_locks.pop(name, None)
            if dropped:
                local_lines.pop(dropped[1], None)
    return aliases, local_locks, local_lines


def _callable_events(
    ctx: FileContext,
    fn: ast.AST,
    ci: _ClassInfo | None,
    data: _ProjectLocks | None,
) -> tuple[list[_Acq], list[_CallSite]]:
    """Walk one function body tracking the lexically-held lock stack:
    every acquisition (``with`` item or explicit ``.acquire()``) and
    every call expression, each tagged with the held set at that
    point. Nested function/class defs are separate callables — their
    bodies do not run under the enclosing ``with``."""
    info = _file_lock_info(ctx)
    aliases, local_locks, local_lines = _local_lock_aliases(fn, ci, info)
    if data is not None:
        # Function-local constructions are graph nodes too: any edge
        # they participate in must resolve to a registered identity
        # (the lockmap artifact pins this — every edge endpoint is a
        # node record).
        for lid, (kind, line) in local_lines.items():
            data.nodes.setdefault(
                lid,
                {
                    "kind": kind,
                    "file": ctx.path,
                    "line": line,
                    "module": info.stem,
                    "class": ci.name if ci is not None else None,
                },
            )
    acqs: list[_Acq] = []
    calls: list[_CallSite] = []

    def resolve_lock(expr: ast.AST) -> tuple[str, str] | None:
        """(kind, lock id) for an expression denoting a known lock."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and ci is not None
            and expr.attr in ci.locks
        ):
            return ci.locks[expr.attr][0], f"{ci.name}.{expr.attr}"
        if isinstance(expr, ast.Name):
            hit = local_locks.get(expr.id) or aliases.get(expr.id)
            if hit:
                return hit
            mod = info.module_locks.get(expr.id)
            if mod:
                return mod[0], f"{info.stem}.{expr.id}"
        return None

    def resolve_call(call: ast.Call):
        if data is None:
            return None
        f = call.func
        if isinstance(f, ast.Attribute):
            m = f.attr
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id == "self" and ci is not None:
                if (ci.name, m) in data.methods:
                    return (ci.name, m)
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and ci is not None
            ):
                tn = data.attr_types.get(ci.name, {}).get(recv.attr)
                if tn == "<builtin>":
                    return None  # dict/list/queue attr: never a project call
                if tn and (tn, m) in data.methods:
                    return (tn, m)
            if m in _BUILTIN_METHODS:
                return None  # too generic for the unique-name fallback
            cands = data.method_classes.get(m, set())
            if len(cands) > 1:
                # Test stubs shadow real serving classes by method name
                # (_StubRouter.pool vs ReplicaRouter.pool). Classes
                # that own no locks cannot contribute acquisitions, so
                # when exactly one candidate does, resolve there.
                cands = {c for c in cands if data.class_locks.get(c)}
            if len(cands) == 1:
                cand = next(iter(cands))
                if (cand, m) in data.methods:
                    return (cand, m)
            return None
        if isinstance(f, ast.Name) and f.id in data.functions:
            return ("", f.id)
        return None

    def scan_expr(node: ast.AST, held: tuple) -> None:
        """Calls inside one expression (lazily-evaluated subtrees —
        nested defs and lambdas — excluded: they run later, possibly
        after the lock is released)."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                continue
            if isinstance(n, ast.Call):
                fnode = n.func
                if (
                    isinstance(fnode, ast.Attribute)
                    and fnode.attr == "acquire"
                ):
                    lk = resolve_lock(fnode.value)
                    if lk:
                        acqs.append(_Acq(lk[1], lk[0], n.lineno, held))
                calls.append(_CallSite(n, resolve_call(n), n.lineno, held))
            stack.extend(ast.iter_child_nodes(n))

    def visit_stmt(st: ast.stmt, held: tuple) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            newheld = held
            for item in st.items:
                scan_expr(item.context_expr, newheld)
                lk = resolve_lock(item.context_expr)
                if lk:
                    acqs.append(_Acq(lk[1], lk[0], item.context_expr.lineno, newheld))
                    newheld = newheld + (
                        _Held(lk[1], lk[0], item.context_expr.lineno),
                    )
            for s in st.body:
                visit_stmt(s, newheld)
            return
        for _, value in ast.iter_fields(st):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        visit_stmt(v, held)
                    elif isinstance(v, ast.AST):
                        scan_expr(v, held)
            elif isinstance(value, ast.AST):
                scan_expr(value, held)

    for s in fn.body:
        visit_stmt(s, ())
    return acqs, calls


# -- the project lock graph (GL008 + tools/lockmap_report.py) ---------------


def build_lock_graph(
    contexts: list[FileContext],
) -> tuple[dict[str, dict], dict[tuple[str, str], list[str]], list[list[str]]]:
    """``(nodes, edges, cycles)`` of the acquires-while-holding graph.

    ``nodes`` maps lock identity -> declaration metadata; ``edges``
    maps ``(held, acquired)`` -> witness path (``file:line`` strings,
    outermost first); ``cycles`` lists node sequences
    ``[A, B, ..., A]`` — an empty list is the shippable state. Edges
    whose inner-acquisition line carries a GL008 suppression are
    omitted (the committed-suppression contract applies to the lint
    gate and the lockmap census equally)."""
    data = _ProjectLocks()
    for ctx in contexts:
        data.add_file(ctx)

    per_callable: dict = {}
    for ctx in contexts:
        info = _file_lock_info(ctx)
        for (cname, mname), (mctx, fn, _) in list(data.methods.items()):
            if mctx is ctx:
                ci = info.classes.get(cname)
                per_callable[(cname, mname)] = (
                    ctx,
                    _callable_events(ctx, fn, ci, data),
                )
        for fname, (fctx, fn) in data.functions.items():
            if fctx is ctx:
                per_callable[("", fname)] = (
                    ctx,
                    _callable_events(ctx, fn, None, data),
                )

    # Fixpoint: summary[key] = lock -> witness chain of file:line hops
    # from the callable's entry to the acquisition.
    summaries: dict = {key: {} for key in per_callable}
    for _ in range(_FIXPOINT_ROUNDS):
        changed = False
        for key, (ctx, (acqs, calls)) in per_callable.items():
            summ = summaries[key]
            for a in acqs:
                if a.lock not in summ:
                    summ[a.lock] = (f"{ctx.path}:{a.line}",)
                    changed = True
            for c in calls:
                if c.key is None or c.key not in summaries:
                    continue
                for lock, chain in summaries[c.key].items():
                    if lock not in summ and len(chain) < _CHAIN_CAP:
                        summ[lock] = (f"{ctx.path}:{c.line}",) + chain
                        changed = True
        if not changed:
            break

    edges: dict[tuple[str, str], list[str]] = {}

    def add_edge(held: _Held, lock: str, witness: list[str]) -> None:
        edges.setdefault((held.lock, lock), witness)

    for key, (ctx, (acqs, calls)) in per_callable.items():
        for a in acqs:
            if ctx.is_suppressed("GL008", a.line):
                continue
            for h in a.held:
                if h.lock == a.lock and h.kind == "RLock":
                    continue  # RLock self-reentrancy is the point of RLock
                add_edge(
                    h,
                    a.lock,
                    [
                        f"{ctx.path}:{h.line} acquires {h.lock}",
                        f"{ctx.path}:{a.line} acquires {a.lock} "
                        f"while holding {h.lock}",
                    ],
                )
        for c in calls:
            if c.key is None or not c.held:
                continue
            if ctx.is_suppressed("GL008", c.line):
                continue
            callee = ".".join(p for p in c.key if p)
            for lock, chain in summaries.get(c.key, {}).items():
                for h in c.held:
                    if h.lock == lock and h.kind == "RLock":
                        continue
                    add_edge(
                        h,
                        lock,
                        [
                            f"{ctx.path}:{h.line} acquires {h.lock}",
                            f"{ctx.path}:{c.line} calls {callee}() "
                            f"while holding {h.lock}",
                            *(f"{hop} (inside {callee})" for hop in chain[:-1]),
                            f"{chain[-1]} acquires {lock}",
                        ],
                    )

    return data.nodes, edges, _find_cycles(edges)


def _find_cycles(
    edges: dict[tuple[str, str], list[str]]
) -> list[list[str]]:
    """Cycle node sequences ``[A, ..., A]``: self-loops, plus one
    representative cycle per distinct node set inside each non-trivial
    strongly connected component (shortest path back to the edge's
    tail). Deduplicated by normalized rotation."""
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    cycles: list[list[str]] = []
    seen: set[tuple[str, ...]] = set()
    for a, b in sorted(edges):
        if a == b:
            cycles.append([a, a])
            continue
        # Shortest path b -> a (BFS); exists iff this edge is in a cycle.
        prev: dict[str, str | None] = {b: None}
        queue = [b]
        while queue and a not in prev:
            cur = queue.pop(0)
            for nxt in sorted(adj.get(cur, ())):
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        if a not in prev:
            continue
        back = [a]  # walk prev links a -> ... -> b
        while prev[back[-1]] is not None:
            back.append(prev[back[-1]])
        # Cycle: the edge a -> b, then the BFS path b -> ... -> a.
        cyc = [a] + back[::-1]  # [a, b, ..., a]
        # Normalize by rotating the (open) cycle to its minimal node.
        body = cyc[:-1]
        i = body.index(min(body))
        norm = tuple(body[i:] + body[:i])
        if norm in seen:
            continue
        seen.add(norm)
        cycles.append(list(norm) + [norm[0]])
    return cycles


@register
class LockOrder(Rule):
    id = "GL008"
    title = "lock-order-inversion"
    hint = (
        "make every thread acquire these locks in one global order "
        "(or collapse them to one lock); docs/static_analysis.md "
        "#the-lock-graph explains how to read the witness paths"
    )

    def check_project(self, project: ProjectContext) -> list[Finding]:
        _, edges, cycles = build_lock_graph(project.contexts)
        findings: list[Finding] = []
        for cyc in cycles:
            if len(cyc) == 2 and cyc[0] == cyc[1]:
                witness = edges[(cyc[0], cyc[0])]
                path, line = _witness_anchor(witness)
                findings.append(
                    Finding(
                        rule=self.id,
                        path=path,
                        line=line,
                        message=(
                            f"non-reentrant lock {cyc[0]} is re-acquired "
                            "while already held (self-deadlock): "
                            + "; ".join(witness)
                        ),
                        hint="use an RLock or split the inner acquisition "
                        "out of the held region",
                    )
                )
                continue
            parts = []
            for u, v in zip(cyc, cyc[1:]):
                witness = edges.get((u, v), [])
                parts.append(f"{u} -> {v} [" + "; ".join(witness) + "]")
            anchor = edges.get((cyc[0], cyc[1]), [""])
            path, line = _witness_anchor(anchor)
            findings.append(
                Finding(
                    rule=self.id,
                    path=path,
                    line=line,
                    message=(
                        "lock-order cycle "
                        + " -> ".join(cyc)
                        + ": "
                        + " | ".join(parts)
                    ),
                    hint=self.hint,
                )
            )
        return findings


def _witness_anchor(witness: list[str]) -> tuple[str, int]:
    """(path, line) of a witness path's innermost hop."""
    for hop in reversed(witness):
        m = re.match(r"(.+?):(\d+)", hop)
        if m:
            return m.group(1), int(m.group(2))
    return "<unknown>", 0


# -- GL009: blocking calls under a held lock --------------------------------

_SOCKET_BLOCKERS = ("recv", "recvfrom", "recv_into", "accept")
_WAIT_BLOCKERS = ("result", "join", "wait", "communicate")
_SUBPROCESS_FNS = ("run", "call", "check_call", "check_output")


def _slow_match(name: str, patterns: list[str]) -> bool:
    for pat in patterns:
        if pat.endswith("*"):
            if name.startswith(pat[:-1]):
                return True
        elif name == pat:
            return True
    return False


def _blocking_reason(call: ast.Call, slow: list[str]) -> str | None:
    """Why this call blocks unboundedly, or None. The wait family is
    clean when bounded (any positional arg or a timeout= keyword);
    socket/subprocess/slow calls block regardless of arguments."""
    t = terminal_name(call.func)
    dn = dotted_name(call.func)
    bounded = bool(call.args) or any(
        kw.arg == "timeout" for kw in call.keywords
    )
    if t in _WAIT_BLOCKERS and not bounded:
        return f"{t}() without a timeout"
    if t in _SOCKET_BLOCKERS:
        return f"socket {t}()"
    if dn.startswith("subprocess.") and t in _SUBPROCESS_FNS:
        return f"{dn}()"
    if dn == "time.sleep":
        return "time.sleep()"
    if _slow_match(t, slow):
        return f"slow callable {t}()"
    return None


def _allowed_annotation(ctx: FileContext, line: int) -> tuple[bool, bool]:
    """``(annotated, has_reason)`` for a ``#: allowed_blocking`` on the
    given line or the line above (above-form must start with ``#:``,
    mirroring GL004's guarded_by contract)."""
    candidates = []
    if 0 < line <= len(ctx.lines):
        candidates.append(ctx.lines[line - 1])
    if line >= 2:
        above = ctx.lines[line - 2].strip()
        if above.startswith("#:"):
            candidates.append(above)
    for text in candidates:
        m = _ALLOWED_RE.search(text)
        if m:
            return True, bool(m.group(1).strip())
    return False, False


@register
class BlockingUnderLock(Rule):
    id = "GL009"
    title = "blocking-call-under-lock"
    hint = (
        "move the call outside the lock (snapshot under the lock, act "
        "after release), bound it with a timeout, or justify it with "
        "`#: allowed_blocking — reason`"
    )

    def check_file(self, ctx: FileContext) -> list[Finding]:
        info = _file_lock_info(ctx)
        findings: list[Finding] = []
        slow = list(ctx.config.slow_callables)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ci = None
            for anc in ctx.ancestors(fn):
                if isinstance(anc, ast.ClassDef):
                    ci = info.classes.get(anc.name)
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break  # nested def: no enclosing-class lock attrs
            _, calls = _callable_events(ctx, fn, ci, None)
            for c in calls:
                if not c.held:
                    continue
                reason = _blocking_reason(c.node, slow)
                if reason is None:
                    continue
                t = terminal_name(c.node.func)
                if t == "wait" and len(c.held) == 1:
                    lk = _receiver_lock(ctx, c.node, ci, info)
                    if lk is not None and lk == c.held[0].lock:
                        # Condition.wait on the ONLY held lock releases
                        # it while waiting — the intended pattern.
                        continue
                annotated, has_reason = _allowed_annotation(ctx, c.line)
                if annotated and has_reason:
                    continue
                held = c.held[-1]
                if annotated:
                    msg = (
                        f"#: allowed_blocking on {reason} under "
                        f"{held.lock} is missing its justification "
                        "(append `— reason`)"
                    )
                else:
                    msg = (
                        f"blocking {reason} inside the held-lock region "
                        f"of {held.lock} (held since line {held.line}) — "
                        "every thread wanting the lock wedges behind it"
                    )
                findings.append(
                    Finding(
                        rule=self.id,
                        path=ctx.path,
                        line=c.line,
                        message=msg,
                        hint=self.hint,
                    )
                )
        return findings


def _receiver_lock(
    ctx: FileContext,
    call: ast.Call,
    ci: _ClassInfo | None,
    info: _FileLockInfo,
) -> str | None:
    """Lock identity of a ``<recv>.wait()`` receiver, when it is one."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if (
        isinstance(recv, ast.Attribute)
        and isinstance(recv.value, ast.Name)
        and recv.value.id == "self"
        and ci is not None
        and recv.attr in ci.locks
    ):
        return f"{ci.name}.{recv.attr}"
    if isinstance(recv, ast.Name) and recv.id in info.module_locks:
        return f"{info.stem}.{recv.id}"
    return None
