"""GL002 — host sync inside compiled ("hot") code.

The whole telemetry subsystem exists because one ``.item()`` /
``float()`` / ``np.asarray`` on a traced value inside the compiled
step turns the async dispatch pipeline into a blocking transfer per
step (docs/observability.md "no host syncs on the hot path"). Under
``jax.jit`` these calls either sync (on concrete values leaked in) or
crash at trace time — both are bugs the type checker can't see.

A function is **hot** when any of:

* it is decorated with ``jax.jit`` (directly or via
  ``functools.partial(jax.jit, ...)``);
* its name (or a lambda) is passed to ``jax.jit(...)`` /
  ``shard_map(...)`` / ``jax.lax.scan`` / ``jax.lax.map`` in the same
  file;
* it is lexically nested inside a builder named in
  ``LintConfig.hot_containers`` (``train_step_body`` /
  ``eval_step_body`` — their inner ``body`` defs are jitted by every
  step builder in the repo);
* it is nested inside another hot function.

Flagged inside hot code: ``.item()``; ``float/int/bool(x)`` on a
non-literal; ``np.asarray`` / ``np.array``; ``jax.device_get``; and
``Tracer`` span calls (``tracer.span(...)`` / ``.add_span`` /
``.start_trace`` / ``.flush`` on any ``*tracer*``-named receiver) —
tracing must stay host-side by construction: inside a compiled body a
span would execute once at TRACE time (timing the Python trace, not
the run) and its clock reads / locked buffer appends are host work the
compiled step must never carry.
"""

from __future__ import annotations

import ast

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    is_jit_expr,
    jit_call_kwargs,
    register,
    terminal_name,
)

#: Call targets that wrap their first positional argument into compiled
#: code (terminal name -> requires-lax-prefix?).
_WRAPPERS = {"jit": False, "shard_map": False, "scan": True, "map": True}

#: obs.tracing.Tracer's recording surface (span sites + the buffer
#: flush). A call to any of these on a receiver whose dotted name
#: mentions "tracer" (``tracer``, ``self._tracer``, ``cfg.tracer``)
#: inside hot code is flagged: host-side tracing of traced-out code is
#: a lie (runs once, at trace time) and pure host work besides.
_TRACER_METHODS = ("span", "add_span", "start_trace", "timed_iter", "flush")


def collect_hot_functions(ctx: FileContext) -> set[ast.AST]:
    """All FunctionDef / Lambda nodes whose bodies execute under a jit
    trace (see module docstring for the sources)."""
    hot: set[ast.AST] = set()
    defs_by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            if any(
                jit_call_kwargs(dec) is not None for dec in node.decorator_list
            ):
                hot.add(node)
    # Names / lambdas handed to jit / shard_map / lax.scan / lax.map.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = terminal_name(node.func)
        if name not in _WRAPPERS:
            continue
        if _WRAPPERS[name] and "lax" not in dotted_name(node.func):
            continue
        if name == "jit" and not is_jit_expr(node.func):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Lambda):
            hot.add(arg)
        elif isinstance(arg, ast.Name):
            hot.update(defs_by_name.get(arg.id, ()))
    # Nested inside hot containers (train_step_body's inner `body`).
    containers = set(ctx.config.hot_containers)
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.Lambda))
            and node not in hot
        ):
            for anc in ctx.ancestors(node):
                if anc in hot or (
                    isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and anc.name in containers
                ):
                    hot.add(node)
                    break
    # Transitive: defs nested in newly-hot functions (one extra pass
    # suffices — ancestors() sees the full chain).
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.Lambda)) and node not in hot:
            if any(anc in hot for anc in ctx.ancestors(node)):
                hot.add(node)
    return hot


def _is_literalish(node: ast.AST) -> bool:
    return isinstance(node, (ast.Constant, ast.JoinedStr)) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant)
    )


def _sync_violation(call: ast.Call) -> str | None:
    """Describe the host sync this call performs, or None."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "item" and not call.args:
        return "`.item()` forces a device->host transfer"
    name = terminal_name(func)
    if (
        isinstance(func, ast.Name)
        and name in ("float", "int", "bool")
        and call.args
        and not _is_literalish(call.args[0])
    ):
        return (
            f"`{name}(...)` on a traced value blocks on the device "
            "(or fails at trace time)"
        )
    if name in ("asarray", "array") and isinstance(func, ast.Attribute):
        base = dotted_name(func.value)
        if base in ("np", "numpy"):
            return f"`{base}.{name}(...)` materializes the value on host"
    if name == "device_get":
        return "`jax.device_get(...)` is a blocking device->host fetch"
    if (
        isinstance(func, ast.Attribute)
        and func.attr in _TRACER_METHODS
        and "tracer" in dotted_name(func.value).lower()
    ):
        return (
            f"`Tracer.{func.attr}(...)` is host-side tracing — inside "
            "compiled code it runs once at trace time (timing the "
            "trace, not the execution) and adds host work per call"
        )
    return None


@register
class HostSyncInHotPath(Rule):
    id = "GL002"
    title = "host-sync-in-hot-path"
    hint = (
        "keep the math in jnp (device-side) and fetch at a drain "
        "boundary (TelemetryBuffer-style), or move the conversion "
        "outside the compiled function"
    )

    def check_file(self, ctx: FileContext) -> list[Finding]:
        hot = collect_hot_functions(ctx)
        if not hot:
            return []
        findings: list[Finding] = []
        for fn in hot:
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                # Nested defs are themselves in `hot` when reachable
                # hot code; walking them here would double-report.
                for node in _walk_shallow(stmt):
                    if isinstance(node, ast.Call):
                        why = _sync_violation(node)
                        if why is not None:
                            findings.append(
                                Finding(
                                    rule=self.id,
                                    path=ctx.path,
                                    line=node.lineno,
                                    message=(
                                        f"host sync inside compiled code "
                                        f"({_fn_label(fn)}): {why}"
                                    ),
                                    hint=self.hint,
                                )
                            )
        uniq = {(f.path, f.line, f.message): f for f in findings}
        return list(uniq.values())


def _walk_shallow(node: ast.AST):
    """Yield ``node`` and descendants WITHOUT descending into nested
    function/lambda bodies (those are analyzed as their own hot fns)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield from _walk_shallow(child)


def _fn_label(fn: ast.AST) -> str:
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return f"function `{fn.name}`"
    return "jitted lambda"
