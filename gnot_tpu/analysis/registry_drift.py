"""GL005 — event/fault/wire/span registry drift.

Four central registries exist so the observability and protocol
surfaces cannot rot silently:

* ``gnot_tpu/obs/events.py`` — every event kind a ``MetricsSink``
  record may carry (name, required payload fields, emitting module);
* ``gnot_tpu/obs/events.py::SPANS`` — every tracer span kind
  (``obs/tracing.py`` / ``obs/dtrace.py`` — the taxonomy
  ``tools/trace_report.py`` groups by);
* ``gnot_tpu/resilience/faults.py::FAULT_KINDS`` — every injectable
  fault kind;
* ``gnot_tpu/serve/federation.py::MESSAGES`` — every federation wire
  message kind (the versioned multi-host protocol).

The rule enforces, per file: every event kind passed to
``sink.log(event=...)`` / ``self._event(...)`` / ``on_event(event=...)``
resolves to an events-registry entry, every wire kind passed to
``wire(X, ...)`` resolves to a MESSAGES entry (string literals and
module-constant references both), and every LITERAL span name passed
to a tracer span site (``span``/``add_span``/``timed_iter``/
``_trace_span``/``_tspan``) resolves to a SPANS entry — in library and
tool code only: tests construct toy spans by design, so ``tests/`` is
exempt from the span-site check (events and wire kinds stay checked
there). Project-wide: every registry entry appears in the user-facing
docs (``docs/observability.md`` for events AND spans,
``docs/robustness.md`` for fault kinds, ``docs/serving.md`` for wire
messages) — the docs are part of the contract, so adding a kind
without documenting it fails tier-1.

Registries are read by AST, not import: the linter must not pay a
jax/numpy import to check a string table.
"""

from __future__ import annotations

import ast
import os
import re

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectContext,
    Rule,
    register,
    terminal_name,
)


def _parse_string_constants(tree: ast.AST) -> dict[str, str]:
    """Top-level ``NAME = "value"`` string assignments."""
    out: dict[str, str] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _parse_registry(path: str) -> tuple[dict[str, int], dict[str, str]]:
    """``(kinds, constants)`` from a registry module's source:
    ``kinds`` maps each registered kind to its declaration line —
    EVENTS dict keys, or FAULT_KINDS/KINDS tuple entries — and
    ``constants`` maps module-level constant names to kind strings."""
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return {}, {}
    kinds: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            names = {node.target.id}
        else:
            continue
        if node.value is None:
            continue
        if names & {"EVENTS", "MESSAGES"} and isinstance(
            node.value, ast.Dict
        ):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    kinds[k.value] = k.lineno
        if names & {"FAULT_KINDS", "KINDS"} and isinstance(
            node.value, (ast.Tuple, ast.List)
        ):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    kinds[e.value] = e.lineno
    return kinds, _parse_string_constants(tree)


class _EmitSite:
    __slots__ = ("kind", "line")

    def __init__(self, kind: str, line: int):
        self.kind = kind
        self.line = line


def _emitted_kinds(
    ctx: FileContext, constants: dict[str, str]
) -> list[_EmitSite]:
    """Event kinds this file passes to a sink: ``*.log(event=X)``,
    ``*._event(X, ...)``, ``*.on_event(event=X)``. ``X`` may be a
    string literal, an ``events.<CONST>`` attribute, or a bare
    imported constant name; dynamic values (locals, parameters) are
    skipped — they are checked at their own literal origin."""
    sites: list[_EmitSite] = []

    def resolve(node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name is not None and name in constants:
            return constants[name]
        return None

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        attr = terminal_name(node.func)
        expr: ast.AST | None = None
        if attr in ("log", "on_event"):
            for kw in node.keywords:
                if kw.arg == "event":
                    expr = kw.value
        elif attr == "_event" and node.args:
            expr = node.args[0]
        if expr is None:
            continue
        kind = resolve(expr)
        if kind is not None:
            sites.append(_EmitSite(kind, expr.lineno))
    return sites


def _parse_spans(path: str) -> tuple[dict[str, int], bool]:
    """``(kinds, declared)``: ``SPANS`` literal-dict keys → declaration
    lines from the events registry module, plus whether a top-level
    ``SPANS`` assignment exists at all. Kept separate from
    ``_parse_registry`` on purpose: span kinds are a sibling namespace
    to event kinds, not a subset — merging them would let a span name
    silence a missing-event finding (and vice versa). ``declared``
    distinguishes a registry that predates SPANS (fixture sandboxes:
    the span checks are simply vacuous) from one whose SPANS table
    fails to parse (a loud project finding)."""
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return {}, False
    kinds: dict[str, int] = {}
    declared = False
    for node in tree.body:
        if isinstance(node, ast.Assign):
            names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            names = {node.target.id}
        else:
            continue
        if node.value is None or "SPANS" not in names:
            continue
        declared = True
        if isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    kinds[k.value] = k.lineno
    return kinds, declared


# Span-recording call sites and which positional argument carries the
# span NAME. ``span``/``add_span`` take it first; ``timed_iter`` takes
# (iterable, name); the ``_trace_span``/``_tspan`` helpers in
# server.py/trainer.py take (trace, name).
_SPAN_CALLS = {
    "span": 0,
    "add_span": 0,
    "timed_iter": 1,
    "_trace_span": 1,
    "_tspan": 1,
}


def _span_sites(ctx: FileContext) -> list[_EmitSite]:
    """Literal span names this file records via a tracer span site.
    Dynamic names (variables, f-strings) are skipped — they are checked
    at their own literal origin, same as event emit sites."""
    sites: list[_EmitSite] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        pos = _SPAN_CALLS.get(terminal_name(node.func))
        if pos is None or len(node.args) <= pos:
            continue
        expr = node.args[pos]
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            sites.append(_EmitSite(expr.value, expr.lineno))
    return sites


def _wire_sites(ctx: FileContext, constants: dict[str, str]) -> list[_EmitSite]:
    """Wire message kinds this file passes to ``wire(X, ...)`` — the
    federation protocol's frame builder. ``X`` may be a string literal
    or a module-level constant (``HELLO``/``federation.HELLO``);
    dynamic values are skipped, same as event emit sites."""
    sites: list[_EmitSite] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if terminal_name(node.func) != "wire":
            continue
        expr = node.args[0]
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            sites.append(_EmitSite(expr.value, expr.lineno))
            continue
        name = None
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        if name is not None and name in constants:
            sites.append(_EmitSite(constants[name], expr.lineno))
    return sites


@register
class RegistryDrift(Rule):
    id = "GL005"
    title = "registry-drift"
    hint = (
        "add the kind to gnot_tpu/obs/events.py (events/SPANS), "
        "resilience/faults.py::FAULT_KINDS (faults) or "
        "serve/federation.py::MESSAGES (wire), and document it in "
        "docs/observability.md / docs/robustness.md / docs/serving.md"
    )

    def __init__(self) -> None:
        self._event_kinds: dict[str, dict[str, int]] = {}
        self._constants: dict[str, dict[str, str]] = {}
        self._msg_kinds: dict[str, dict[str, int]] = {}
        self._msg_constants: dict[str, dict[str, str]] = {}
        self._span_kinds: dict[str, tuple[dict[str, int], bool]] = {}

    def _registry(self, root: str, cfg) -> tuple[dict[str, int], dict[str, str]]:
        key = root
        if key not in self._event_kinds:
            kinds, constants = _parse_registry(
                os.path.join(root, cfg.events_registry)
            )
            self._event_kinds[key] = kinds
            self._constants[key] = constants
        return self._event_kinds[key], self._constants[key]

    def _messages(self, root: str, cfg) -> tuple[dict[str, int], dict[str, str]]:
        key = root
        if key not in self._msg_kinds:
            kinds, constants = _parse_registry(
                os.path.join(root, cfg.messages_registry)
            )
            self._msg_kinds[key] = kinds
            self._msg_constants[key] = constants
        return self._msg_kinds[key], self._msg_constants[key]

    def _spans(self, root: str, cfg) -> tuple[dict[str, int], bool]:
        key = root
        if key not in self._span_kinds:
            self._span_kinds[key] = _parse_spans(
                os.path.join(root, cfg.events_registry)
            )
        return self._span_kinds[key]

    def check_file(self, ctx: FileContext) -> list[Finding]:
        kinds, constants = self._registry(ctx.root, ctx.config)
        findings: list[Finding] = []
        if kinds:
            for site in _emitted_kinds(ctx, constants):
                if site.kind not in kinds:
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=ctx.path,
                            line=site.line,
                            message=(
                                f"event kind {site.kind!r} is not in the "
                                f"central registry ({ctx.config.events_registry})"
                            ),
                            hint=self.hint,
                        )
                    )
        span_kinds, _ = self._spans(ctx.root, ctx.config)
        rel = ctx.path.replace(os.sep, "/")
        # tests/ is exempt from the SPAN-site check only: test suites
        # construct toy spans ("outer", "orphan", ...) to exercise the
        # tracer itself. Event and wire checks still apply there.
        if span_kinds and not (
            rel.startswith("tests/") or "/tests/" in rel
        ):
            for site in _span_sites(ctx):
                if site.kind not in span_kinds:
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=ctx.path,
                            line=site.line,
                            message=(
                                f"span kind {site.kind!r} is not in the "
                                f"SPANS registry "
                                f"({ctx.config.events_registry})"
                            ),
                            hint=self.hint,
                        )
                    )
        # No registry in this tree (fixture sandboxes): the
        # project-level pass reports the missing registry instead.
        msg_kinds, msg_constants = self._messages(ctx.root, ctx.config)
        if msg_kinds:
            # The registry module defines its constants; a CALLER file
            # referencing federation.HELLO resolves through them too.
            lookup = dict(msg_constants)
            lookup.update(_parse_string_constants(ctx.tree))
            for site in _wire_sites(ctx, lookup):
                if site.kind not in msg_kinds:
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=ctx.path,
                            line=site.line,
                            message=(
                                f"wire message kind {site.kind!r} is not "
                                "in the MESSAGES registry "
                                f"({ctx.config.messages_registry})"
                            ),
                            hint=self.hint,
                        )
                    )
        return findings

    def check_project(self, project: ProjectContext) -> list[Finding]:
        cfg = project.config
        findings: list[Finding] = []
        ev_path = os.path.join(project.root, cfg.events_registry)
        if not os.path.exists(ev_path):
            return []  # fixture sandboxes carry no registry
        kinds, _ = self._registry(project.root, cfg)
        if not kinds:
            # The registry EXISTS but EVENTS did not parse as a literal
            # dict: the per-file emit checks were all vacuous this run.
            # That must be a loud finding, not a silent rule shutdown.
            return [
                Finding(
                    rule=self.id,
                    path=cfg.events_registry,
                    line=1,
                    message=(
                        "EVENTS is not parseable as a literal dict of "
                        "string keys — GL005 cannot check emit sites "
                        "against it"
                    ),
                    hint="keep EVENTS a literal {str: EventSpec} dict",
                )
            ]
        findings.extend(
            self._docs_coverage(
                project.root, cfg.events_registry, kinds, cfg.docs_events
            )
        )
        span_kinds, spans_declared = self._spans(project.root, cfg)
        if spans_declared and not span_kinds:
            # Same loudness contract as EVENTS/MESSAGES: a declared
            # SPANS table that fails to parse as a literal dict would
            # silently disable every span-site check — surface it. A
            # registry with NO SPANS assignment (fixture sandboxes)
            # simply has the span plane vacuous.
            findings.append(
                Finding(
                    rule=self.id,
                    path=cfg.events_registry,
                    line=1,
                    message=(
                        "SPANS is not parseable as a literal dict of "
                        "string keys — GL005 cannot check span sites "
                        "against it"
                    ),
                    hint="keep SPANS a literal {str: SpanSpec} dict",
                )
            )
        elif span_kinds:
            findings.extend(
                self._docs_coverage(
                    project.root,
                    cfg.events_registry,
                    span_kinds,
                    cfg.docs_events,
                )
            )
        fault_kinds, _ = _parse_registry(
            os.path.join(project.root, cfg.faults_registry)
        )
        findings.extend(
            self._docs_coverage(
                project.root, cfg.faults_registry, fault_kinds, cfg.docs_faults
            )
        )
        msg_path = os.path.join(project.root, cfg.messages_registry)
        if os.path.exists(msg_path):
            msg_kinds, _ = self._messages(project.root, cfg)
            if not msg_kinds:
                # Same loudness contract as EVENTS: an existing wire
                # registry that fails to parse silently disables every
                # wire-site check — surface it.
                findings.append(
                    Finding(
                        rule=self.id,
                        path=cfg.messages_registry,
                        line=1,
                        message=(
                            "MESSAGES is not parseable as a literal dict "
                            "of string keys — GL005 cannot check wire "
                            "sites against it"
                        ),
                        hint="keep MESSAGES a literal {str: MessageSpec} "
                        "dict",
                    )
                )
            else:
                findings.extend(
                    self._docs_coverage(
                        project.root,
                        cfg.messages_registry,
                        msg_kinds,
                        cfg.docs_messages,
                    )
                )
        return findings

    def _docs_coverage(
        self, root: str, reg_rel: str, kinds: dict[str, int], doc_rel: str
    ) -> list[Finding]:
        doc_path = os.path.join(root, doc_rel)
        try:
            with open(doc_path, encoding="utf-8") as f:
                doc = f.read()
        except OSError:
            return [
                Finding(
                    rule=self.id,
                    path=reg_rel,
                    line=1,
                    message=f"registry documented in missing file {doc_rel}",
                    hint=self.hint,
                )
            ]
        return [
            Finding(
                rule=self.id,
                path=reg_rel,
                line=line,
                message=(
                    f"registry entry {kind!r} is not documented in "
                    f"{doc_rel}"
                ),
                hint=self.hint,
            )
            for kind, line in sorted(kinds.items(), key=lambda kv: kv[1])
            # "Documented" = appears as a code token: `kind` exactly, or
            # `kind@...` (the fault-spec form). A bare prose mention
            # ("reloads are retried") must NOT count.
            if not re.search(rf"`{re.escape(kind)}[`@]", doc)
        ]
