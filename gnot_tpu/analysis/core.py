"""graftlint framework: rule registry, per-file driver, suppressions.

Pure stdlib (``ast`` + ``re``) by design — the analysis reads source,
never imports the code under test, so a broken or device-hungry module
still lints. Rules subclass :class:`Rule` and register via
``@register``; each rule sees one :class:`FileContext` per file (parsed
tree, parent links, raw lines) and may also implement a project-level
pass (:meth:`Rule.check_project`) for cross-file invariants.

Suppressions (``docs/static_analysis.md``):

* ``# graftlint: disable=GL001`` on the offending line silences that
  rule there (comma-separate several ids; append ``— reason`` — every
  committed suppression must carry one).
* ``# graftlint: disable-file=GL002`` anywhere in a file silences the
  rule for the whole file.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import os
import re
import tokenize
from typing import Iterable

#: Rule-id grammar: GL + digits (or "all"). The capture is anchored to
#: id tokens so a trailing justification — with or without a dash —
#: is never swallowed into the id list.
_IDS = r"(?:[A-Za-z]+\d+|all|ALL)(?:\s*,\s*(?:[A-Za-z]+\d+|all|ALL))*"
_SUPPRESS_RE = re.compile(rf"#\s*graftlint:\s*disable=({_IDS})")
_SUPPRESS_FILE_RE = re.compile(rf"#\s*graftlint:\s*disable-file=({_IDS})")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to ``file:line`` with a fix hint.

    ``project_level`` marks findings from a rule's cross-file pass
    (GL005 registry/docs drift): they are caused by the change set as
    a whole, so diff-scoped reporting (``tools/lint.py --changed``)
    must never filter them by path."""

    rule: str  # "GL001"
    path: str  # repo-relative
    line: int
    message: str
    hint: str = ""
    project_level: bool = False

    def format(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            s += f" [hint: {self.hint}]"
        return s

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintConfig:
    """Per-run configuration (``[tool.graftlint]`` in pyproject.toml).

    ``enable``/``disable`` select rules by id; ``exclude`` drops files
    whose repo-relative path matches any glob (or contains it as a
    substring — ``"native/"`` excludes the whole dir). Rule-specific
    knobs carry their rule id in the name.
    """

    enable: list[str] = dataclasses.field(default_factory=list)  # [] = all
    disable: list[str] = dataclasses.field(default_factory=list)
    exclude: list[str] = dataclasses.field(default_factory=list)
    # Default scan roots for the CLI (no positional paths) and the
    # tier-1 repo-tree-clean gate. tests/ and tools/ are in: every
    # historical use-after-donate instance lived there.
    paths: list[str] = dataclasses.field(
        default_factory=lambda: ["gnot_tpu", "tests", "tools"]
    )
    # GL001: terminal attribute/function names known to donate arg 0
    # (the builders in train/trainer.py, obs/telemetry.py,
    # parallel/mesh.py and parallel/pipeline.py all donate the state).
    donate_callables: list[str] = dataclasses.field(
        default_factory=lambda: ["train_step", "multi_train_step"]
    )
    # GL002: builder functions whose NESTED defs are compiled step
    # bodies (train_step_body's `body` is jitted by every step builder).
    hot_containers: list[str] = dataclasses.field(
        default_factory=lambda: ["train_step_body", "eval_step_body"]
    )
    # GL005: registry + docs locations (repo-relative).
    events_registry: str = "gnot_tpu/obs/events.py"
    faults_registry: str = "gnot_tpu/resilience/faults.py"
    messages_registry: str = "gnot_tpu/serve/federation.py"
    docs_events: str = "docs/observability.md"
    docs_faults: str = "docs/robustness.md"
    docs_messages: str = "docs/serving.md"
    # GL007: the ctypes bindings module and the C source whose
    # extern "C" declarations it must match (arity + dtype tags).
    native_binding: str = "gnot_tpu/native/__init__.py"
    native_source: str = "gnot_tpu/native/ragged_pack.cpp"
    # GL009: terminal names of project callables known to block for
    # "long" (dispatch/compile/IO scale, not counter-bump scale) —
    # calling one inside a held-lock region wedges every sibling
    # thread. A trailing "*" makes the entry a prefix match
    # ("infer*" covers infer/infer_batch/infer_packed/infer_session).
    slow_callables: list[str] = dataclasses.field(
        default_factory=lambda: [
            "infer*",
            "warmup",
            "aot_compile",
            "save_checkpoint",
            "restore_checkpoint",
            "reload",
        ]
    )
    # GL010: the config dataclasses, the CLI that must wire them, and
    # the docs where every knob must be mentioned.
    config_module: str = "gnot_tpu/config.py"
    cli_module: str = "gnot_tpu/main.py"
    # "<mapping prefix>:<dataclass name>" pairs: every field of the
    # class must appear as a "<prefix>.<field>" key in the CLI's
    # config mapping, and vice versa.
    config_sections: list[str] = dataclasses.field(
        default_factory=lambda: ["train:TrainConfig", "serve:ServeConfig"]
    )
    docs_config: list[str] = dataclasses.field(
        default_factory=lambda: [
            "docs/serving.md",
            "docs/robustness.md",
            "docs/observability.md",
        ]
    )

    def rule_enabled(self, rule_id: str) -> bool:
        if rule_id in self.disable:
            return False
        return not self.enable or rule_id in self.enable

    def excludes(self, rel_path: str) -> bool:
        rel = rel_path.replace(os.sep, "/")
        return any(
            fnmatch.fnmatch(rel, pat) or pat in rel for pat in self.exclude
        )


def _parse_toml_value(raw: str):
    raw = raw.strip()
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip().rstrip(",")
        if not inner:
            return []
        return [_parse_toml_value(v) for v in _split_toml_list(inner)]
    if raw.startswith(('"', "'")):
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        return raw


def _strip_toml_comment(line: str) -> str:
    """Drop an inline ``# ...`` comment (quote-aware)."""
    quote = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _split_toml_list(inner: str) -> list[str]:
    out, depth, cur, quote = [], 0, "", None
    for ch in inner:
        if quote:
            cur += ch
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur += ch
        elif ch == "[":
            depth += 1
            cur += ch
        elif ch == "]":
            depth -= 1
            cur += ch
        elif ch == "," and depth == 0:
            out.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        out.append(cur)
    return [s.strip() for s in out]


def _read_graftlint_section(pyproject_path: str) -> dict:
    """Parse the ``[tool.graftlint]`` table. Uses tomllib when the
    interpreter has it; otherwise a minimal hand parser covering the
    subset this section uses (strings, string arrays, bools, ints —
    multiline arrays included). The image's python predates tomllib
    and nothing heavier may be installed, hence the fallback."""
    try:
        with open(pyproject_path, "rb") as f:
            data = f.read().decode("utf-8")
    except OSError:
        return {}
    try:
        import tomllib  # py >= 3.11

        try:
            return tomllib.loads(data).get("tool", {}).get("graftlint", {})
        except tomllib.TOMLDecodeError:
            pass  # fall through to the lenient hand parser
    except ImportError:
        pass
    out: dict = {}
    in_section = False
    pending_key = None
    pending_val = ""
    for line in data.splitlines():
        stripped = _strip_toml_comment(line).strip()
        if pending_key is not None:
            pending_val += " " + stripped
            if stripped.endswith("]"):
                out[pending_key] = _parse_toml_value(pending_val)
                pending_key, pending_val = None, ""
            continue
        if stripped.startswith("["):
            in_section = stripped == "[tool.graftlint]"
            continue
        if not in_section or not stripped:
            continue
        if "=" not in stripped:
            continue
        key, _, val = stripped.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("[") and not val.endswith("]"):
            pending_key, pending_val = key, val  # multiline array
            continue
        out[key] = _parse_toml_value(val)
    return out


def load_config(root: str) -> LintConfig:
    """LintConfig from ``<root>/pyproject.toml``'s ``[tool.graftlint]``
    (defaults when the file or section is absent)."""
    section = _read_graftlint_section(os.path.join(root, "pyproject.toml"))
    cfg = LintConfig()
    for field in dataclasses.fields(LintConfig):
        if field.name in section:
            setattr(cfg, field.name, section[field.name])
    return cfg


class FileContext:
    """One parsed file handed to each rule: tree with parent links,
    raw lines (rules read annotation comments the AST drops), and the
    per-line suppression map."""

    def __init__(
        self,
        root: str,
        rel_path: str,
        source: str,
        config: "LintConfig | None" = None,
    ):
        self.root = root
        self.path = rel_path
        self.source = source
        self.config = config or LintConfig()
        # Back-reference to the run's ProjectContext (set by
        # run_analysis). Rules must degrade gracefully when None — unit
        # fixtures construct FileContexts directly.
        self.project: "ProjectContext | None" = None
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel_path)
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.suppressed: dict[int, set[str]] = {}
        self.file_suppressed: set[str] = set()
        # Real COMMENT tokens only — a docstring merely *documenting*
        # the suppression syntax must not suppress anything.
        for line_no, comment in self._comments(source):
            m = _SUPPRESS_RE.search(comment)
            if m:
                self.suppressed.setdefault(line_no, set()).update(
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                )
            m = _SUPPRESS_FILE_RE.search(comment)
            if m:
                self.file_suppressed |= {
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                }

    @staticmethod
    def _comments(source: str) -> list[tuple[int, str]]:
        try:
            return [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError):
            # ast.parse succeeded, so this should be unreachable; stay
            # permissive rather than dropping all suppressions.
            return []

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_statement(self, node: ast.AST) -> ast.stmt:
        cur = node
        while not isinstance(cur, ast.stmt):
            cur = self._parents[cur]
        return cur

    def enclosing_function(
        self, node: ast.AST
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if rule_id in self.file_suppressed or "ALL" in self.file_suppressed:
            return True
        rules = self.suppressed.get(line, ())
        return rule_id in rules or "ALL" in rules


class ProjectContext:
    """Cross-file state for project-level checks (GL005 docs drift) and
    the donation call graph GL001/GL006 resolve helper wrappers
    through (``build_donation_graph``)."""

    def __init__(self, root: str, config: LintConfig):
        self.root = root
        self.config = config
        #: FileContexts of every parsed file in this run (set by
        #: run_analysis before any rule executes).
        self.contexts: list[FileContext] = []
        #: terminal callable name -> Donor. Seeded from the configured
        #: donate_callables, grown to fixpoint over helper wrappers.
        self.donors: dict[str, "Donor"] = {}
        #: factory name -> Donor of the callable it RETURNS
        #: (``make_train_step`` returns a jitted donating step).
        self.factories: dict[str, "Donor"] = {}


class Rule:
    """Base rule: subclass, set ``id``/``title``, implement
    ``check_file`` (and optionally ``check_project`` for cross-file
    invariants — called once, after every file)."""

    id: str = ""
    title: str = ""
    hint: str = ""

    def check_file(self, ctx: FileContext) -> list[Finding]:
        return []

    def check_project(self, project: ProjectContext) -> list[Finding]:
        return []


#: id -> rule class. Populated by the ``@register`` decorator at import
#: of the rule modules (analysis/__init__ imports them all).
RULES: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.id or cls.id in RULES:
        raise ValueError(f"bad or duplicate rule id: {cls.id!r}")
    RULES[cls.id] = cls
    return cls


def iter_python_files(paths: list[str], root: str, config: LintConfig):
    """Yield repo-relative .py paths under ``paths`` (files or dirs),
    honoring ``config.exclude``. Deterministic order."""
    seen = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            seen.append(os.path.relpath(full, root))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    seen.append(
                        os.path.relpath(os.path.join(dirpath, name), root)
                    )
    return [rel for rel in seen if not config.excludes(rel)]


def run_analysis(
    paths: list[str],
    *,
    root: str,
    config: LintConfig | None = None,
) -> tuple[list[Finding], dict]:
    """Run every enabled rule over every python file under ``paths``.

    Returns ``(findings, stats)`` where stats counts files scanned and
    suppressions honored. Findings are sorted by (path, line, rule).
    A file that fails to parse yields a synthetic ``GL000`` finding
    instead of crashing the run (the lint gate must report, not die).
    """
    config = config or load_config(root)
    rules = [
        cls() for rid, cls in sorted(RULES.items()) if config.rule_enabled(rid)
    ]
    findings: list[Finding] = []
    n_suppressed = 0
    files = iter_python_files(paths, root, config)
    # Phase 1 — parse everything. The donation call graph (GL001/GL006)
    # needs every file's tree before any per-file rule runs: a test
    # calls a trainer method that calls the donating step, and only the
    # project-wide fixpoint sees that chain.
    contexts: list[FileContext] = []
    for rel in files:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                contexts.append(FileContext(root, rel, f.read(), config))
        except (OSError, SyntaxError, UnicodeDecodeError, ValueError) as err:
            findings.append(
                Finding(
                    rule="GL000",
                    path=rel,
                    line=getattr(err, "lineno", 0) or 0,
                    message=f"could not analyze file: {err}",
                    hint="fix the syntax error or exclude the file",
                )
            )
    # Phase 2 — project context + donation call graph.
    project = ProjectContext(root, config)
    project.contexts = contexts
    project.donors, project.factories = build_donation_graph(contexts, config)
    # Phase 3 — per-file rules (each ctx sees the project graph).
    for ctx in contexts:
        ctx.project = project
        for rule in rules:
            for f in rule.check_file(ctx):
                if ctx.is_suppressed(f.rule, f.line):
                    n_suppressed += 1
                else:
                    findings.append(f)
    for rule in rules:
        findings.extend(
            dataclasses.replace(f, project_level=True)
            for f in rule.check_project(project)
        )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = {
        "files": len(files),
        "rules": [r.id for r in rules],
        "suppressed": n_suppressed,
        "findings": len(findings),
    }
    return findings, stats


# -- shared AST helpers (used by several rules) ----------------------------


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of a call target: ``jax.lax.scan`` ->
    "jax.lax.scan"; unresolvable pieces become ``?``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    return "?"


def terminal_name(node: ast.AST) -> str:
    """Final attribute/name of a call target (``self.train_step`` ->
    "train_step")."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` as an expression."""
    return terminal_name(node) == "jit"


def jit_call_kwargs(dec: ast.AST) -> dict[str, ast.AST] | None:
    """If ``dec`` is a jit-producing decorator/call, return its keyword
    args (possibly empty). Recognized shapes: ``jax.jit``,
    ``jax.jit(...)``, ``functools.partial(jax.jit, ...)``."""
    if is_jit_expr(dec):
        return {}
    if isinstance(dec, ast.Call):
        if is_jit_expr(dec.func):
            return {k.arg: k.value for k in dec.keywords if k.arg}
        if terminal_name(dec.func) == "partial" and dec.args:
            if is_jit_expr(dec.args[0]):
                return {k.arg: k.value for k in dec.keywords if k.arg}
    return None


def full_key(node: ast.AST) -> str | None:
    """Stable dotted identity of a trackable expression: a name
    (``state``), an attribute path rooted at a name
    (``self.state.params``), or either through a subscript
    (``state.params["w"]`` -> "state.params"). None for anything whose
    identity the analysis cannot track (call results, literals)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = full_key(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Subscript):
        return full_key(node.value)
    return None


def keys_related(a: str, b: str) -> bool:
    """Whether two expression keys can alias the same buffers: equal,
    or one a dotted prefix of the other (``state`` donated frees the
    buffers a ``state.params`` view aliases, and vice versa)."""
    return a == b or a.startswith(b + ".") or b.startswith(a + ".")


# -- donation call graph (GL001 / GL006) ------------------------------------


@dataclasses.dataclass(frozen=True)
class Donor:
    """How a callable donates device buffers.

    ``arg_positions`` — positions into a *bound* call's arguments that
    are donated (``train_step(state, batch, lr)`` donates position 0).
    ``self_attrs`` — receiver attributes the callable donates
    internally (``Trainer.fit`` donates ``self.state`` through its
    nested dispatch helpers), so ``t.fit()`` makes host views of
    ``t.state...`` stale.
    """

    arg_positions: tuple[int, ...] = ()
    self_attrs: tuple[str, ...] = ()

    def merged(self, other: "Donor") -> "Donor":
        return Donor(
            arg_positions=tuple(
                sorted(set(self.arg_positions) | set(other.arg_positions))
            ),
            self_attrs=tuple(
                sorted(set(self.self_attrs) | set(other.self_attrs))
            ),
        )

    def __bool__(self) -> bool:
        return bool(self.arg_positions or self.self_attrs)


def donated_indices(kwargs: dict[str, ast.AST]) -> tuple[int, ...]:
    """The ``donate_argnums`` of a jit call's keyword dict."""
    node = kwargs.get("donate_argnums")
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def collect_jit_donating(tree: ast.AST) -> dict[str, tuple[int, ...]]:
    """Intra-file donating callables: defs decorated
    ``@partial(jax.jit, donate_argnums=...)`` and names bound via
    ``f = jax.jit(g, donate_argnums=...)``. File-local by design — a
    generic local name like ``step`` must not leak into the project
    graph and flag unrelated files."""
    donating: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                kwargs = jit_call_kwargs(dec)
                if kwargs:
                    idxs = donated_indices(kwargs)
                    if idxs:
                        donating[node.name] = idxs
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            kwargs = jit_call_kwargs(node.value) or (
                {k.arg: k.value for k in node.value.keywords if k.arg}
                if terminal_name(node.value.func) == "jit"
                else None
            )
            if kwargs:
                idxs = donated_indices(kwargs)
                if idxs:
                    for t in node.targets:
                        name = terminal_name(t)
                        if name:
                            donating[name] = idxs
    return donating


def _param_names(fn: ast.AST) -> list[str]:
    args = fn.args
    return [a.arg for a in (*args.posonlyargs, *args.args)]


def _resolve_call_donor(
    call: ast.Call,
    donors: dict[str, Donor],
    local: dict[str, tuple[int, ...]],
) -> Donor | None:
    name = terminal_name(call.func)
    d = donors.get(name)
    idxs = local.get(name)
    if idxs:
        d = (d or Donor()).merged(Donor(arg_positions=idxs))
    return d


def donated_keys_of_call(
    call: ast.Call,
    donors: dict[str, Donor],
    local: dict[str, tuple[int, ...]] | None = None,
) -> list[str]:
    """Expression keys whose device buffers are dead after ``call``:
    donated positional args, plus ``<receiver>.<attr>`` for every
    self-attribute the callee donates internally (``t.fit()`` with
    ``fit`` donating ``self.state`` kills ``t.state``)."""
    d = _resolve_call_donor(call, donors, local or {})
    if not d:
        return []
    keys: list[str] = []
    for p in d.arg_positions:
        if p < len(call.args):
            k = full_key(call.args[p])
            if k:
                keys.append(k)
    if d.self_attrs and isinstance(call.func, ast.Attribute):
        rk = full_key(call.func.value)
        if rk:
            keys.extend(f"{rk}.{a}" for a in d.self_attrs)
    return keys


def _function_donation(
    fn: ast.AST,
    donors: dict[str, Donor],
    local: dict[str, tuple[int, ...]],
) -> Donor:
    """What ``fn`` donates of ITS OWN interface, judged by the calls in
    its body (nested helper defs included — the trainer's dispatch
    closures donate ``self.state`` on the enclosing method's behalf):
    a parameter passed into a donating call in donated position makes
    ``fn`` a positional donor; a donated ``self.<attr>`` makes it a
    self-attribute donor."""
    params = _param_names(fn)
    is_method = bool(params) and params[0] in ("self", "cls")
    positions: set[int] = set()
    attrs: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or node is fn:
            continue
        for key in donated_keys_of_call(node, donors, local):
            base = key.split(".")[0]
            if key.startswith("self.") and "." in key:
                attrs.add(key.split(".")[1])
            elif base in params:
                pos = params.index(base)
                call_pos = pos - 1 if is_method else pos
                if call_pos >= 0:
                    positions.add(call_pos)
    return Donor(
        arg_positions=tuple(sorted(positions)), self_attrs=tuple(sorted(attrs))
    )


def _returned_donor(
    fn: ast.AST,
    donors: dict[str, Donor],
    local: dict[str, tuple[int, ...]],
    factories: dict[str, Donor],
) -> Donor:
    """Donor of the callable ``fn`` RETURNS, if any — the step-factory
    shape. Recognized returns: a local jitted-donating def
    (``make_train_step``), a direct ``return jax.jit(step, ...,
    donate_argnums=...)`` (``make_sharded_train_step``), and a
    delegation to another known factory
    (``return pipeline.make_pipelined_train_step(...)``). Assignments
    ``step = make_train_step(...)`` then make the local name a donor
    (``factory_assigned_donors``)."""
    own_jit = collect_jit_donating(fn)
    out = Donor()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Return) and node.value is not None):
            continue
        v = node.value
        if isinstance(v, ast.Call):
            kwargs = jit_call_kwargs(v)
            if kwargs:
                idxs = donated_indices(kwargs)
                if idxs:
                    out = out.merged(Donor(arg_positions=idxs))
                continue
            fac = factories.get(terminal_name(v.func))
            if fac:
                out = out.merged(fac)
            continue
        name = terminal_name(v)
        idxs = own_jit.get(name) or local.get(name)
        if idxs:
            out = out.merged(Donor(arg_positions=idxs))
        elif name in donors:
            out = out.merged(donors[name])
    return out


def build_donation_graph(
    contexts: list["FileContext"], config: LintConfig
) -> tuple[dict[str, Donor], dict[str, Donor]]:
    """Project-wide donation call graph, to fixpoint.

    Seeds: the configured ``donate_callables`` (arg 0). Each round, a
    function that feeds one of its parameters (or a ``self.<attr>``)
    into a known donating call becomes a donor itself — so calls
    through helper indirection (``run_single``-style wrappers,
    ``Trainer.fit``) resolve without per-call configuration. Intra-file
    jitted donors participate in their own file's propagation but stay
    file-local (generic names must not flag other files). Also returns
    the factory map: functions returning a donating callable
    (``make_train_step``)."""
    donors: dict[str, Donor] = {
        name: Donor(arg_positions=(0,)) for name in config.donate_callables
    }
    factories: dict[str, Donor] = {}
    local_by_ctx = []
    for ctx in contexts:
        local = collect_jit_donating(ctx.tree)
        # Stash for donors_for_file — the per-rule resolution reuses
        # this instead of re-walking the tree.
        ctx._jit_donors = local
        local_by_ctx.append(local)
    for _ in range(8):  # bounded fixpoint; chains are short in practice
        changed = False
        for ctx, local in zip(contexts, local_by_ctx):
            for fn in ast.walk(ctx.tree):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                d = _function_donation(fn, donors, local)
                if d:
                    merged = donors.get(fn.name, Donor()).merged(d)
                    if merged != donors.get(fn.name):
                        donors[fn.name] = merged
                        changed = True
                f = _returned_donor(fn, donors, local, factories)
                if f:
                    fmerged = factories.get(fn.name, Donor()).merged(f)
                    if fmerged != factories.get(fn.name):
                        factories[fn.name] = fmerged
                        changed = True
        if not changed:
            break
    return donors, factories


def donors_for_file(ctx: "FileContext") -> dict[str, Donor]:
    """The donor map one file's rules should resolve calls against:
    configured donate_callables + the project graph + file-local jit
    donors + factory assignments. A project entry whose name is
    shadowed by a local def is kept only if THIS file's def donates too
    (a generic helper name in another file must not flag this one).
    Memoized per FileContext — GL001 and GL006 both resolve through
    this and the local-defs rescan is pure repetition."""
    cached = getattr(ctx, "_donors_cache", None)
    if cached is not None:
        return cached
    local = getattr(ctx, "_jit_donors", None)
    if local is None:  # direct FileContext use (unit fixtures)
        local = collect_jit_donating(ctx.tree)
    out: dict[str, Donor] = {
        name: Donor(arg_positions=(0,)) for name in ctx.config.donate_callables
    }
    project = ctx.project
    if project is not None:
        local_defs: dict[str, list[ast.AST]] = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.setdefault(n.name, []).append(n)
        for name, d in project.donors.items():
            if name in out:
                out[name] = out[name].merged(d)
                continue
            defs = local_defs.get(name)
            if defs and name not in local:
                own = Donor()
                for fn in defs:
                    own = own.merged(
                        _function_donation(fn, project.donors, local)
                    )
                if own:
                    out[name] = own
            else:
                out[name] = d
        for name, idxs in factory_assigned_donors(
            ctx.tree, project.factories
        ).items():
            out[name] = out.get(name, Donor()).merged(
                Donor(arg_positions=idxs)
            )
    for name, idxs in local.items():
        out[name] = out.get(name, Donor()).merged(Donor(arg_positions=idxs))
    ctx._donors_cache = out
    return out


def factory_assigned_donors(
    tree: ast.AST, factories: dict[str, Donor]
) -> dict[str, tuple[int, ...]]:
    """File-local donors from factory assignments:
    ``step = make_train_step(...)`` binds a name that donates exactly
    what the factory's returned callable donates."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        fac = factories.get(terminal_name(node.value.func))
        if not fac or not fac.arg_positions:
            continue
        for t in node.targets:
            name = terminal_name(t)
            if name:
                out[name] = fac.arg_positions
    return out
