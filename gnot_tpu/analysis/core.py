"""graftlint framework: rule registry, per-file driver, suppressions.

Pure stdlib (``ast`` + ``re``) by design — the analysis reads source,
never imports the code under test, so a broken or device-hungry module
still lints. Rules subclass :class:`Rule` and register via
``@register``; each rule sees one :class:`FileContext` per file (parsed
tree, parent links, raw lines) and may also implement a project-level
pass (:meth:`Rule.check_project`) for cross-file invariants.

Suppressions (``docs/static_analysis.md``):

* ``# graftlint: disable=GL001`` on the offending line silences that
  rule there (comma-separate several ids; append ``— reason`` — every
  committed suppression must carry one).
* ``# graftlint: disable-file=GL002`` anywhere in a file silences the
  rule for the whole file.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import os
import re
import tokenize
from typing import Iterable

#: Rule-id grammar: GL + digits (or "all"). The capture is anchored to
#: id tokens so a trailing justification — with or without a dash —
#: is never swallowed into the id list.
_IDS = r"(?:[A-Za-z]+\d+|all|ALL)(?:\s*,\s*(?:[A-Za-z]+\d+|all|ALL))*"
_SUPPRESS_RE = re.compile(rf"#\s*graftlint:\s*disable=({_IDS})")
_SUPPRESS_FILE_RE = re.compile(rf"#\s*graftlint:\s*disable-file=({_IDS})")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to ``file:line`` with a fix hint."""

    rule: str  # "GL001"
    path: str  # repo-relative
    line: int
    message: str
    hint: str = ""

    def format(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            s += f" [hint: {self.hint}]"
        return s

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintConfig:
    """Per-run configuration (``[tool.graftlint]`` in pyproject.toml).

    ``enable``/``disable`` select rules by id; ``exclude`` drops files
    whose repo-relative path matches any glob (or contains it as a
    substring — ``"native/"`` excludes the whole dir). Rule-specific
    knobs carry their rule id in the name.
    """

    enable: list[str] = dataclasses.field(default_factory=list)  # [] = all
    disable: list[str] = dataclasses.field(default_factory=list)
    exclude: list[str] = dataclasses.field(default_factory=list)
    # GL001: terminal attribute/function names known to donate arg 0
    # (the builders in train/trainer.py, obs/telemetry.py,
    # parallel/mesh.py and parallel/pipeline.py all donate the state).
    donate_callables: list[str] = dataclasses.field(
        default_factory=lambda: ["train_step", "multi_train_step"]
    )
    # GL002: builder functions whose NESTED defs are compiled step
    # bodies (train_step_body's `body` is jitted by every step builder).
    hot_containers: list[str] = dataclasses.field(
        default_factory=lambda: ["train_step_body", "eval_step_body"]
    )
    # GL005: registry + docs locations (repo-relative).
    events_registry: str = "gnot_tpu/obs/events.py"
    faults_registry: str = "gnot_tpu/resilience/faults.py"
    docs_events: str = "docs/observability.md"
    docs_faults: str = "docs/robustness.md"

    def rule_enabled(self, rule_id: str) -> bool:
        if rule_id in self.disable:
            return False
        return not self.enable or rule_id in self.enable

    def excludes(self, rel_path: str) -> bool:
        rel = rel_path.replace(os.sep, "/")
        return any(
            fnmatch.fnmatch(rel, pat) or pat in rel for pat in self.exclude
        )


def _parse_toml_value(raw: str):
    raw = raw.strip()
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip().rstrip(",")
        if not inner:
            return []
        return [_parse_toml_value(v) for v in _split_toml_list(inner)]
    if raw.startswith(('"', "'")):
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        return raw


def _strip_toml_comment(line: str) -> str:
    """Drop an inline ``# ...`` comment (quote-aware)."""
    quote = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _split_toml_list(inner: str) -> list[str]:
    out, depth, cur, quote = [], 0, "", None
    for ch in inner:
        if quote:
            cur += ch
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur += ch
        elif ch == "[":
            depth += 1
            cur += ch
        elif ch == "]":
            depth -= 1
            cur += ch
        elif ch == "," and depth == 0:
            out.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        out.append(cur)
    return [s.strip() for s in out]


def _read_graftlint_section(pyproject_path: str) -> dict:
    """Parse the ``[tool.graftlint]`` table. Uses tomllib when the
    interpreter has it; otherwise a minimal hand parser covering the
    subset this section uses (strings, string arrays, bools, ints —
    multiline arrays included). The image's python predates tomllib
    and nothing heavier may be installed, hence the fallback."""
    try:
        with open(pyproject_path, "rb") as f:
            data = f.read().decode("utf-8")
    except OSError:
        return {}
    try:
        import tomllib  # py >= 3.11

        try:
            return tomllib.loads(data).get("tool", {}).get("graftlint", {})
        except tomllib.TOMLDecodeError:
            pass  # fall through to the lenient hand parser
    except ImportError:
        pass
    out: dict = {}
    in_section = False
    pending_key = None
    pending_val = ""
    for line in data.splitlines():
        stripped = _strip_toml_comment(line).strip()
        if pending_key is not None:
            pending_val += " " + stripped
            if stripped.endswith("]"):
                out[pending_key] = _parse_toml_value(pending_val)
                pending_key, pending_val = None, ""
            continue
        if stripped.startswith("["):
            in_section = stripped == "[tool.graftlint]"
            continue
        if not in_section or not stripped:
            continue
        if "=" not in stripped:
            continue
        key, _, val = stripped.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("[") and not val.endswith("]"):
            pending_key, pending_val = key, val  # multiline array
            continue
        out[key] = _parse_toml_value(val)
    return out


def load_config(root: str) -> LintConfig:
    """LintConfig from ``<root>/pyproject.toml``'s ``[tool.graftlint]``
    (defaults when the file or section is absent)."""
    section = _read_graftlint_section(os.path.join(root, "pyproject.toml"))
    cfg = LintConfig()
    for field in dataclasses.fields(LintConfig):
        if field.name in section:
            setattr(cfg, field.name, section[field.name])
    return cfg


class FileContext:
    """One parsed file handed to each rule: tree with parent links,
    raw lines (rules read annotation comments the AST drops), and the
    per-line suppression map."""

    def __init__(
        self,
        root: str,
        rel_path: str,
        source: str,
        config: "LintConfig | None" = None,
    ):
        self.root = root
        self.path = rel_path
        self.source = source
        self.config = config or LintConfig()
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel_path)
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.suppressed: dict[int, set[str]] = {}
        self.file_suppressed: set[str] = set()
        # Real COMMENT tokens only — a docstring merely *documenting*
        # the suppression syntax must not suppress anything.
        for line_no, comment in self._comments(source):
            m = _SUPPRESS_RE.search(comment)
            if m:
                self.suppressed.setdefault(line_no, set()).update(
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                )
            m = _SUPPRESS_FILE_RE.search(comment)
            if m:
                self.file_suppressed |= {
                    r.strip().upper() for r in m.group(1).split(",") if r.strip()
                }

    @staticmethod
    def _comments(source: str) -> list[tuple[int, str]]:
        try:
            return [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError):
            # ast.parse succeeded, so this should be unreachable; stay
            # permissive rather than dropping all suppressions.
            return []

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_statement(self, node: ast.AST) -> ast.stmt:
        cur = node
        while not isinstance(cur, ast.stmt):
            cur = self._parents[cur]
        return cur

    def enclosing_function(
        self, node: ast.AST
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if rule_id in self.file_suppressed or "ALL" in self.file_suppressed:
            return True
        rules = self.suppressed.get(line, ())
        return rule_id in rules or "ALL" in rules


class ProjectContext:
    """Cross-file state for project-level checks (GL005 docs drift)."""

    def __init__(self, root: str, config: LintConfig):
        self.root = root
        self.config = config


class Rule:
    """Base rule: subclass, set ``id``/``title``, implement
    ``check_file`` (and optionally ``check_project`` for cross-file
    invariants — called once, after every file)."""

    id: str = ""
    title: str = ""
    hint: str = ""

    def check_file(self, ctx: FileContext) -> list[Finding]:
        return []

    def check_project(self, project: ProjectContext) -> list[Finding]:
        return []


#: id -> rule class. Populated by the ``@register`` decorator at import
#: of the rule modules (analysis/__init__ imports them all).
RULES: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.id or cls.id in RULES:
        raise ValueError(f"bad or duplicate rule id: {cls.id!r}")
    RULES[cls.id] = cls
    return cls


def iter_python_files(paths: list[str], root: str, config: LintConfig):
    """Yield repo-relative .py paths under ``paths`` (files or dirs),
    honoring ``config.exclude``. Deterministic order."""
    seen = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            seen.append(os.path.relpath(full, root))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    seen.append(
                        os.path.relpath(os.path.join(dirpath, name), root)
                    )
    return [rel for rel in seen if not config.excludes(rel)]


def run_analysis(
    paths: list[str],
    *,
    root: str,
    config: LintConfig | None = None,
) -> tuple[list[Finding], dict]:
    """Run every enabled rule over every python file under ``paths``.

    Returns ``(findings, stats)`` where stats counts files scanned and
    suppressions honored. Findings are sorted by (path, line, rule).
    A file that fails to parse yields a synthetic ``GL000`` finding
    instead of crashing the run (the lint gate must report, not die).
    """
    config = config or load_config(root)
    rules = [
        cls() for rid, cls in sorted(RULES.items()) if config.rule_enabled(rid)
    ]
    findings: list[Finding] = []
    n_suppressed = 0
    files = iter_python_files(paths, root, config)
    for rel in files:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                ctx = FileContext(root, rel, f.read(), config)
        except (OSError, SyntaxError, UnicodeDecodeError, ValueError) as err:
            findings.append(
                Finding(
                    rule="GL000",
                    path=rel,
                    line=getattr(err, "lineno", 0) or 0,
                    message=f"could not analyze file: {err}",
                    hint="fix the syntax error or exclude the file",
                )
            )
            continue
        for rule in rules:
            for f in rule.check_file(ctx):
                if ctx.is_suppressed(f.rule, f.line):
                    n_suppressed += 1
                else:
                    findings.append(f)
    project = ProjectContext(root, config)
    for rule in rules:
        findings.extend(rule.check_project(project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = {
        "files": len(files),
        "rules": [r.id for r in rules],
        "suppressed": n_suppressed,
        "findings": len(findings),
    }
    return findings, stats


# -- shared AST helpers (used by several rules) ----------------------------


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of a call target: ``jax.lax.scan`` ->
    "jax.lax.scan"; unresolvable pieces become ``?``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    return "?"


def terminal_name(node: ast.AST) -> str:
    """Final attribute/name of a call target (``self.train_step`` ->
    "train_step")."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` as an expression."""
    return terminal_name(node) == "jit"


def jit_call_kwargs(dec: ast.AST) -> dict[str, ast.AST] | None:
    """If ``dec`` is a jit-producing decorator/call, return its keyword
    args (possibly empty). Recognized shapes: ``jax.jit``,
    ``jax.jit(...)``, ``functools.partial(jax.jit, ...)``."""
    if is_jit_expr(dec):
        return {}
    if isinstance(dec, ast.Call):
        if is_jit_expr(dec.func):
            return {k.arg: k.value for k in dec.keywords if k.arg}
        if terminal_name(dec.func) == "partial" and dec.args:
            if is_jit_expr(dec.args[0]):
                return {k.arg: k.value for k in dec.keywords if k.arg}
    return None
