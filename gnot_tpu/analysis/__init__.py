"""graftlint: JAX-aware static analysis for this codebase's invariants.

Three invariant classes here are load-bearing and, before this package,
were enforced only by comments and reviewer vigilance:

* **buffer-donation safety** — the jitted train steps donate the
  TrainState (``donate_argnums=(0,)``); reading a donated buffer after
  the call is use-after-free on device memory (PR 2 fixed a real one
  that silently corrupted checkpoints). Rule **GL001**.
* **no host syncs on hot paths** — one ``.item()`` inside a compiled
  step body turns an async dispatch pipeline into a lock-step crawl;
  the whole telemetry design exists to avoid it. Rules **GL002**
  (host sync in compiled code) and **GL003** (recompile hazards).
* **lock discipline** — the threaded serving layer shares mutable
  counters between the client, worker, and reload threads; a missed
  ``with self._lock`` is a data race that only shows up under storm
  traffic. Rule **GL004**.
* **registry drift** — event kinds and fault kinds each have a central
  registry (``obs/events.py``, ``resilience/faults.py::FAULT_KINDS``)
  and user-facing docs; an emit site or registry entry that drifts from
  them is an observability hole. Rule **GL005**.
* **lock ordering and convoys** — the serving/federation planes hold
  locks while calling into code that takes other locks; a cycle in the
  project-wide acquires-while-holding graph is a deadlock no per-file
  view can see, and a blocking call under a held lock is a convoy.
  Rules **GL008** (lock-order inversion, via the cross-file lock graph
  — published as ``docs/artifacts/lockmap.jsonl``) and **GL009**
  (blocking-call-under-lock, ``#: allowed_blocking — reason`` to
  justify); ``utils/lockguard.py`` is the runtime witness for what the
  AST cannot resolve.
* **config drift** — a ``ServeConfig``/``TrainConfig`` field that no
  CLI flag reaches (or a ``config_from_args`` key naming a ghost
  field, or a field no docs page mentions) is dead configuration that
  looks alive. Rule **GL010**.

The framework (``core.py``) is pure stdlib ``ast`` — the analysis
itself never imports the code under test, touches no devices, and
scans the whole tree in under a second (``tools/lint.py`` stubs the
package import so the CLI skips the jax import entirely). Rules
register themselves via
``@register``; ``run_analysis`` drives them per file plus one
project-level pass (docs drift). Findings carry ``file:line`` plus a
fix hint; ``# graftlint: disable=RULE — reason`` suppresses one line.

Usage: ``python tools/lint.py gnot_tpu`` (docs/static_analysis.md).
"""

from gnot_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintConfig,
    Rule,
    RULES,
    load_config,
    register,
    run_analysis,
)

# Importing the rule modules registers them.
from gnot_tpu.analysis import aliasing  # noqa: F401
from gnot_tpu.analysis import config_drift  # noqa: F401
from gnot_tpu.analysis import donation  # noqa: F401
from gnot_tpu.analysis import hostsync  # noqa: F401
from gnot_tpu.analysis import lockorder  # noqa: F401
from gnot_tpu.analysis import locks  # noqa: F401
from gnot_tpu.analysis import native_abi  # noqa: F401
from gnot_tpu.analysis import recompile  # noqa: F401
from gnot_tpu.analysis import registry_drift  # noqa: F401
