"""GL004 — lock discipline for annotated shared state.

The serving layer shares mutable counters and the published weight
reference between the client thread (``submit``/``reload``/``drain``),
the worker thread, and reload callers. The convention: a field declared
with a ``#: guarded_by <lock>`` annotation comment

.. code-block:: python

    self._completed = 0  #: guarded_by _lock

may only be touched inside a ``with self.<lock>`` block. The rule reads
the annotation comments straight from the source lines (the AST drops
comments), then checks every ``self.<attr>`` load/store in the class.

Exemptions: ``__init__`` (the object is not shared while it is being
constructed) and the annotated declaration lines themselves. Anything
else — including "it's only read" accesses: torn reads of a dict or
list during a concurrent resize are real — must hold the lock or carry
a justified ``# graftlint: disable=GL004 — reason`` suppression.
"""

from __future__ import annotations

import ast
import re

from gnot_tpu.analysis.core import FileContext, Finding, Rule, register

_GUARD_RE = re.compile(r"#:\s*guarded_by\s+(\w+)")


@register
class LockDiscipline(Rule):
    id = "GL004"
    title = "lock-discipline"
    hint = (
        "wrap the access in `with self.<lock>:` (or move it into an "
        "existing locked block); if the access is provably "
        "single-threaded, suppress with a justification"
    )

    def check_file(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(ctx, cls))
        return findings

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> list[Finding]:
        guarded, decl_lines = self._guarded_attrs(ctx, cls)
        if not guarded:
            return []
        findings: list[Finding] = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue  # not shared during construction
            for node in ast.walk(method):
                if not (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in guarded
                ):
                    continue
                if node.lineno in decl_lines:
                    continue
                lock = guarded[node.attr]
                if self._under_lock(ctx, node, lock):
                    continue
                access = (
                    "written" if isinstance(node.ctx, ast.Store) else "read"
                )
                findings.append(
                    Finding(
                        rule=self.id,
                        path=ctx.path,
                        line=node.lineno,
                        message=(
                            f"`self.{node.attr}` (guarded_by {lock}) "
                            f"{access} outside `with self.{lock}` in "
                            f"`{cls.name}.{method.name}`"
                        ),
                        hint=self.hint,
                    )
                )
        return findings

    def _guarded_attrs(
        self, ctx: FileContext, cls: ast.ClassDef
    ) -> tuple[dict[str, str], set[int]]:
        """``{attr: lock_name}`` from ``#: guarded_by`` comments on (or
        immediately above) ``self.<attr> = ...`` lines, plus the
        declaration line numbers (exempt from the check)."""
        guarded: dict[str, str] = {}
        decl_lines: set[int] = set()
        for node in ast.walk(cls):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                lock = self._annotation_at(ctx, t.lineno)
                if lock is not None:
                    guarded[t.attr] = lock
                    decl_lines.add(t.lineno)
        return guarded, decl_lines

    @staticmethod
    def _annotation_at(ctx: FileContext, lineno: int) -> str | None:
        line = ctx.lines[lineno - 1] if lineno <= len(ctx.lines) else ""
        m = _GUARD_RE.search(line)
        if m:
            return m.group(1)
        prev = ctx.lines[lineno - 2].strip() if lineno >= 2 else ""
        if prev.startswith("#:"):
            m = _GUARD_RE.search(prev)
            if m:
                return m.group(1)
        return None

    @staticmethod
    def _under_lock(ctx: FileContext, node: ast.AST, lock: str) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False  # don't credit an outer function's lock
            if isinstance(anc, ast.With):
                for item in anc.items:
                    e = item.context_expr
                    if (
                        isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr == lock
                    ):
                        return True
        return False
