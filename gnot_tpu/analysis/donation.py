"""GL001 — use-after-donate.

The jitted train steps donate their first argument
(``donate_argnums=(0,)``): after the call, the TrainState's device
buffers are XLA's to reuse, and reading them is use-after-free — the
exact bug PR 2's drive-by fixed, where async orbax saves read donated
buffers and silently corrupted mid-run checkpoints.

Donating-callable discovery (``core.donors_for_file``):

* **intra-file** — any function defined with a
  ``@functools.partial(jax.jit, donate_argnums=...)`` decorator (or
  bound via ``f = jax.jit(g, donate_argnums=...)``), called later in
  the same file;
* **configured** — calls whose terminal name is in
  ``LintConfig.donate_callables`` (default ``train_step`` /
  ``multi_train_step`` — the trainer's step attributes, built by
  donating builders in train/trainer.py, obs/telemetry.py,
  parallel/mesh.py, parallel/pipeline.py);
* **call graph** (``core.build_donation_graph``) — helper wrappers that
  feed a parameter into a donating call in donated position
  (``run_single``-style), resolved project-wide to fixpoint, plus
  file-local names bound from step FACTORIES
  (``step = make_train_step(...)``). Only positional donors extend this
  rule — self-attribute donors (``Trainer.fit`` donating
  ``self.state``) are GL006's aliased-host-view territory, where the
  hazard needs an outstanding host view, not a missing rebind.

A call is SAFE when the donated expression is rebound by the same
statement (``state, loss = step(state, ...)``) — the canonical
pattern. Otherwise any later read of that expression in the enclosing
function before a rebind is flagged; a call inside a loop whose
donated expression is never rebound in the loop is flagged too (the
next iteration re-reads the donated buffer).
"""

from __future__ import annotations

import ast

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    donors_for_file,
    full_key,
    register,
    terminal_name,
)


def _matches_key(node: ast.AST, key: str) -> bool:
    return full_key(node) == key


def _assigned_keys(stmt: ast.stmt) -> set[str]:
    """Expression keys (re)bound by this statement's targets."""
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.With):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    out: set[str] = set()
    for t in targets:
        for node in ast.walk(t):
            key = full_key(node)
            if key is not None:
                out.add(key)
    return out


@register
class UseAfterDonate(Rule):
    id = "GL001"
    title = "use-after-donate"
    hint = (
        "rebind the donated value in the call statement "
        "(`state, out = step(state, ...)`) or take a device copy "
        "(`jnp.copy`) before the donating call"
    )

    def check_file(self, ctx: FileContext) -> list[Finding]:
        donating = {
            name: d.arg_positions
            for name, d in donors_for_file(ctx).items()
            if d.arg_positions
        }
        findings: list[Finding] = []
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            name = terminal_name(call.func)
            idxs = donating.get(name)
            if idxs is None:
                continue
            for idx in idxs:
                if idx >= len(call.args):
                    continue
                key = full_key(call.args[idx])
                if key is None:
                    continue  # a fresh expression; nothing to re-read
                bad_line = self._use_after(ctx, call, key)
                if bad_line is not None:
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=ctx.path,
                            line=bad_line,
                            message=(
                                f"`{key}` is read after being donated to "
                                f"`{name}(...)` (donate_argnums arg {idx}, "
                                f"call at line {call.lineno}); the donated "
                                f"device buffers are dead"
                            ),
                            hint=self.hint,
                        )
                    )
        return findings

    # -- dataflow ----------------------------------------------------------

    def _use_after(
        self, ctx: FileContext, call: ast.Call, key: str
    ) -> int | None:
        """Line of the first read of ``key`` after the donating call
        and before a rebind, or None when the pattern is safe."""
        stmt = ctx.enclosing_statement(call)
        if key in _assigned_keys(stmt):
            return None  # canonical `x, ... = step(x, ...)` rebind
        func = ctx.enclosing_function(call)
        scope: ast.AST = func if func is not None else ctx.tree
        # Ordered (position, kind, line) events for the key across the
        # scope; "after" is by source position — a conservative stand-in
        # for execution order within one function body.
        events: list[tuple[int, int, str, int]] = []
        for node in ast.walk(scope):
            k = None
            if isinstance(node, (ast.Name, ast.Attribute)) and _matches_key(
                node, key
            ):
                k = "store" if isinstance(node.ctx, ast.Store) else "load"
            if k is not None:
                events.append((node.lineno, node.col_offset, k, node.lineno))
        events.sort()
        # "After" = strictly past the call expression's END, so reads
        # inside the (possibly multiline) call itself never count.
        call_end = (call.end_lineno, call.end_col_offset)
        after = [e for e in events if (e[0], e[1]) > call_end]
        for _, _, kind, line in after:
            if kind == "store":
                break
            return line
        if "." in key and not any(k == "store" for _, _, k, _ in after):
            # A donated ATTRIBUTE (`self.state`) that this scope never
            # rebinds: the attribute keeps pointing at freed buffers
            # for every later reader — including the enclosing method
            # when the call sits in a nested helper (the scan cannot
            # see past the def boundary, so the absence of a rebind IS
            # the finding). A donated plain local with no later use is
            # just dead and stays unflagged.
            return call.lineno
        # Loop case: the call re-executes; if the key is never rebound
        # anywhere inside the loop, the next iteration reads the
        # donated buffer through the call's own argument.
        loop = None
        for anc in ctx.ancestors(call):
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                loop = anc
                break
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        if loop is not None:
            for node in ast.walk(loop):
                if (
                    isinstance(node, (ast.Name, ast.Attribute))
                    and _matches_key(node, key)
                    and isinstance(node.ctx, ast.Store)
                ):
                    return None
            if key in _assigned_keys(loop):
                return None
            return call.lineno
        return None
