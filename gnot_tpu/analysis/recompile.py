"""GL003 — recompile hazards.

Two shapes of "compiles O(traffic) programs instead of O(1)":

* ``jax.jit`` / ``shard_map`` / ``jax.pmap`` invoked inside a loop
  body — every iteration builds a NEW wrapper whose trace cache is
  thrown away, so every call compiles. The repo's discipline is
  build-once (all step builders run at initialize(); the serving
  engine compiles one program per bucket). A jit in a loop silently
  breaks the O(log L_max) compiled-program bound the chaos suite
  asserts.
* a jitted function whose **static** argument has a non-hashable
  default (list/dict/set): jit hashes static args to key the trace
  cache, so the first call with the default raises — or, with a
  converted-to-tuple workaround upstream, churns the cache when the
  caller rebuilds the default per call.
"""

from __future__ import annotations

import ast

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    is_jit_expr,
    jit_call_kwargs,
    register,
    terminal_name,
)

_COMPILING = ("jit", "pmap", "shard_map")
_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp)


def _is_compiling_call(node: ast.Call) -> bool:
    name = terminal_name(node.func)
    if name not in _COMPILING:
        return False
    if name == "jit":
        return is_jit_expr(node.func)
    if name == "pmap":
        return "jax" in dotted_name(node.func) or isinstance(
            node.func, ast.Name
        )
    return True  # shard_map (ops.collectives or jax.experimental)


def _static_indices(kwargs: dict[str, ast.AST]) -> tuple[int, ...]:
    node = kwargs.get("static_argnums")
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def _static_names(kwargs: dict[str, ast.AST]) -> tuple[str, ...]:
    node = kwargs.get("static_argnames")
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


@register
class RecompileHazard(Rule):
    id = "GL003"
    title = "recompile-hazard"
    hint = (
        "hoist the jit/shard_map wrapper out of the loop (build once, "
        "call many); make static-arg defaults hashable (tuple, not "
        "list)"
    )

    def check_file(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        findings.extend(self._jit_in_loop(ctx))
        findings.extend(self._mutable_static_defaults(ctx))
        return findings

    def _jit_in_loop(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_compiling_call(node)):
                continue
            # Loop ancestry within the same function scope only: a def
            # built inside a loop is a builder the loop calls once each
            # — still suspect, but crossing the def boundary would flag
            # every factory; the in-scope case is the unambiguous bug.
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    break
                if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                    out.append(
                        Finding(
                            rule=self.id,
                            path=ctx.path,
                            line=node.lineno,
                            message=(
                                f"`{dotted_name(node.func)}(...)` invoked "
                                f"inside a loop (line {anc.lineno}): every "
                                f"iteration re-traces and re-compiles"
                            ),
                            hint=self.hint,
                        )
                    )
                    break
        return out

    def _mutable_static_defaults(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                kwargs = jit_call_kwargs(dec)
                if kwargs is None:
                    continue
                idxs = _static_indices(kwargs)
                names = _static_names(kwargs)
                if not idxs and not names:
                    continue
                args = node.args
                params = args.posonlyargs + args.args
                # Defaults right-align onto the positional params.
                offset = len(params) - len(args.defaults)
                for i, default in enumerate(args.defaults):
                    p = params[offset + i]
                    if (
                        (offset + i) in idxs or p.arg in names
                    ) and isinstance(default, _MUTABLE_DEFAULTS):
                        out.append(
                            Finding(
                                rule=self.id,
                                path=ctx.path,
                                line=default.lineno,
                                message=(
                                    f"static arg `{p.arg}` of jitted "
                                    f"`{node.name}` has a non-hashable "
                                    f"default: jit cannot cache-key it"
                                ),
                                hint=self.hint,
                            )
                        )
                for i, default in enumerate(args.kw_defaults):
                    if default is None:
                        continue
                    p = args.kwonlyargs[i]
                    if p.arg in names and isinstance(
                        default, _MUTABLE_DEFAULTS
                    ):
                        out.append(
                            Finding(
                                rule=self.id,
                                path=ctx.path,
                                line=default.lineno,
                                message=(
                                    f"static arg `{p.arg}` of jitted "
                                    f"`{node.name}` has a non-hashable "
                                    f"default: jit cannot cache-key it"
                                ),
                                hint=self.hint,
                            )
                        )
        return out
