"""GL010 — config drift: dataclass knobs vs CLI flags vs docs.

``TrainConfig``/``ServeConfig`` are the operator surface: every field
is a promise that a run can be configured that way. The promise rots
in three directions, each observed in review at least once:

* a field lands with no ``--`` flag — reachable from library code
  only, invisible to ``python -m gnot_tpu.main --help``;
* the CLI mapping in ``main.py::config_from_args`` references a field
  (or an ``args.<flag>``) that no longer exists — a typo that
  ``make_config`` may only reject at run time;
* the knob is documented nowhere — ``docs/serving.md`` /
  ``robustness.md`` / ``observability.md`` never mention it.

The rule closes the triangle, project-wide and AST-only (GL005's
discipline: registries are *parsed*, never imported): every field of
the configured dataclasses must appear as a ``"<section>.<field>"``
key in the CLI module's config mapping, every such key must name a
real field, every ``args.<flag>`` the mapping reads must be a declared
``--<flag>``, and every field must be mentioned in at least one
configured doc — as a backticked code token (`` `field` ``) or as its
flag spelling (``--flag``, fenced command lines count). Suppressions
anchor at the field's declaration line in the config module.
"""

from __future__ import annotations

import ast
import os
import re

from gnot_tpu.analysis.core import (
    FileContext,
    Finding,
    ProjectContext,
    Rule,
    register,
)


def _dataclass_fields(tree: ast.Module, class_name: str) -> dict[str, int]:
    """``field -> declaration line`` for one dataclass, by AST."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {
                st.target.id: st.lineno
                for st in node.body
                if isinstance(st, ast.AnnAssign)
                and isinstance(st.target, ast.Name)
            }
    return {}


def _declared_flags(tree: ast.Module) -> set[str]:
    """Flag names from every ``*.add_argument("--name", ...)``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            continue
        for a in node.args:
            if (
                isinstance(a, ast.Constant)
                and isinstance(a.value, str)
                and a.value.startswith("--")
            ):
                out.add(a.value[2:])
    return out


def _config_mapping(
    tree: ast.Module, prefixes: tuple[str, ...]
) -> dict[str, tuple[int, set[str]]]:
    """``"section.field" -> (line, {args attributes read})`` from every
    dict literal whose string keys carry a configured section prefix —
    the ``config_from_args`` mapping, without naming the function."""
    out: dict[str, tuple[int, set[str]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, value in zip(node.keys, node.values):
            if not (
                isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and key.value.partition(".")[0] in prefixes
                and "." in key.value
            ):
                continue
            refs = {
                n.attr
                for n in ast.walk(value)
                if isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "args"
            }
            if key.value not in out:
                out[key.value] = (key.lineno, refs)
    return out


def _parse_module(root: str, rel: str) -> ast.Module | None:
    try:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            return ast.parse(f.read(), filename=rel)
    except (OSError, SyntaxError):
        return None


def _doc_mentions(root: str, docs: list[str]) -> str:
    chunks = []
    for rel in docs:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                chunks.append(f.read())
        except OSError:
            pass
    return "\n".join(chunks)


def _documented(field: str, flags: set[str], corpus: str) -> bool:
    """Mentioned as a code token: `` `field` `` (optionally dotted or
    ``--``-prefixed inside the backticks) or a ``--flag`` occurrence —
    fenced command lines count, bare prose does not."""
    toks = {field} | flags
    for tok in toks:
        if re.search(rf"`(--|[\w.]+\.)?{re.escape(tok)}[`@ =]", corpus):
            return True
        if re.search(rf"(^|[^\w-])--{re.escape(tok)}\b", corpus):
            return True
    return False


@register
class ConfigDrift(Rule):
    id = "GL010"
    title = "config-drift"
    hint = (
        "wire the field through main.py (add_argument + the "
        "config_from_args mapping) and mention it in docs/serving.md, "
        "docs/robustness.md or docs/observability.md — or delete the "
        "dead knob"
    )

    def check_project(self, project: ProjectContext) -> list[Finding]:
        cfg = project.config
        cfg_path = os.path.join(project.root, cfg.config_module)
        cli_path = os.path.join(project.root, cfg.cli_module)
        if not (os.path.exists(cfg_path) and os.path.exists(cli_path)):
            return []  # fixture sandboxes without a config surface
        cfg_tree = _parse_module(project.root, cfg.config_module)
        cli_tree = _parse_module(project.root, cfg.cli_module)
        if cfg_tree is None or cli_tree is None:
            return []  # unparseable files already carry a GL000
        sections: list[tuple[str, str]] = []
        for spec in cfg.config_sections:
            prefix, _, cls = spec.partition(":")
            if prefix and cls:
                sections.append((prefix, cls))
        # The configured files' FileContexts, for suppression anchoring.
        by_path = {c.path: c for c in project.contexts}
        cfg_ctx = by_path.get(cfg.config_module)
        cli_ctx = by_path.get(cfg.cli_module)

        flags = _declared_flags(cli_tree)
        mapping = _config_mapping(
            cli_tree, tuple(p for p, _ in sections)
        )
        corpus = _doc_mentions(project.root, cfg.docs_config)
        findings: list[Finding] = []

        def emit(ctx: FileContext | None, path: str, line: int, msg: str):
            if ctx is not None and ctx.is_suppressed(self.id, line):
                return
            findings.append(
                Finding(
                    rule=self.id, path=path, line=line, message=msg,
                    hint=self.hint,
                )
            )

        all_fields: set[str] = set()
        for prefix, cls in sections:
            fields = _dataclass_fields(cfg_tree, cls)
            if not fields:
                # The class EXISTS in config (sections name it) but has
                # no parseable annotated fields: every check below
                # would be vacuous — say so loudly (GL005 contract).
                emit(
                    cfg_ctx,
                    cfg.config_module,
                    1,
                    f"config section {prefix!r}: dataclass {cls} has no "
                    "parseable annotated fields — GL010 cannot check "
                    "its CLI/docs wiring",
                )
                continue
            for field, line in sorted(fields.items(), key=lambda kv: kv[1]):
                key = f"{prefix}.{field}"
                all_fields.add(key)
                wired = mapping.get(key)
                if wired is None:
                    emit(
                        cfg_ctx,
                        cfg.config_module,
                        line,
                        f"config field {key} has no CLI wiring in "
                        f"{cfg.cli_module} (no {key!r} entry in the "
                        "config mapping)",
                    )
                    field_flags: set[str] = set()
                else:
                    _, refs = wired
                    field_flags = refs & flags
                    for ref in sorted(refs - flags):
                        emit(
                            cli_ctx,
                            cfg.cli_module,
                            wired[0],
                            f"config mapping {key!r} reads args.{ref} "
                            f"but no --{ref} flag is declared",
                        )
                if not _documented(field, field_flags, corpus):
                    emit(
                        cfg_ctx,
                        cfg.config_module,
                        line,
                        f"config field {key} is not documented in any "
                        f"of {', '.join(cfg.docs_config)} (mention "
                        f"`{field}` or its --flag)",
                    )
        for key, (line, _) in sorted(mapping.items()):
            if key not in all_fields:
                emit(
                    cli_ctx,
                    cfg.cli_module,
                    line,
                    f"config mapping key {key!r} does not match any "
                    f"field of the configured dataclasses in "
                    f"{cfg.config_module}",
                )
        return findings
