"""Host-side structured span tracing — the third leg of the
observability stack (metrics -> events -> traces).

Telemetry (PR 1) says *that* a step was slow and the event stream
(PR 2-4) says *that* a request was shed; neither says *where the time
went*. GNOT's ragged point-cloud meshes make latency intrinsically
shape-dependent — bucketed padding means queue-wait, pad waste, compile
and device time all vary per bucket — so this module records wall-time
spans on the HOST side of every phase and exports them as Chrome
trace-event JSON (loadable in ``chrome://tracing`` / Perfetto, no
TensorBoard required).

Design constraints (docs/observability.md "Tracing"):

* **No device syncs.** A span is two reads of an injectable monotonic
  clock plus one locked list append. Nothing here touches jax values;
  the graftlint rule GL002 flags any ``Tracer`` call that leaks inside
  a compiled step body (host tracing of traced-out code is a lie — the
  span would time the trace, not the execution).
* **Head-based sampling.** The keep/drop decision is made once per
  trace at :meth:`Tracer.start_trace` (deterministic, counter-based —
  no RNG, so tests and replays sample identically); an unsampled trace
  costs one ``None`` check per span site.
* **Bounded buffer, explicit flush.** At most ``max_spans`` spans are
  held in memory; further spans are counted as ``dropped`` instead of
  growing without bound. :meth:`Tracer.flush` writes the file (and
  optionally a ``trace_flush`` event through the MetricsSink).
* **Device-timeline bridge.** With ``annotate=True`` every span also
  enters ``utils/profiling.annotate`` (``jax.profiler``
  TraceAnnotation), so when ``--profile_dir`` is set the host spans
  appear on the XLA timeline under the same names.

Span taxonomy (the contract ``tools/trace_report.py`` groups by):

* serving, per request (one ``trace_id`` per submitted request):
  ``admission -> queue_wait -> batch_assembly -> dispatch -> device ->
  unpad -> resolve``; batch-level phases are recorded once per member
  request with the member's ``trace_id`` and a ``member_trace_ids``
  arg linking the co-dispatched requests.
* training, per epoch (one ``trace_id`` per epoch): an ``epoch`` root
  with ``data_iter`` / ``step`` (containing ``host_to_device`` and
  ``step_dispatch``) / ``telemetry_drain`` / ``eval`` /
  ``checkpoint_save`` children.

Ambient nesting uses a :mod:`contextvars` context variable, so spans
opened on one thread parent correctly under that thread's enclosing
span while other threads (the serve worker vs. its clients) keep their
own chains.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import math
import os
import threading
import time
from typing import Callable, Iterable, Iterator

from gnot_tpu.obs import events

#: Serve-side span names, in request-lifecycle order (docs/serving.md).
#: Every completed request gets exactly this chain under one trace_id.
SERVE_SPANS = (
    "admission",
    "queue_wait",
    "batch_assembly",
    "dispatch",
    "device",
    "unpad",
    "resolve",
)

#: Optional serve-side spans a request chain MAY additionally carry:
#: ``compile`` marks a fresh-signature jit dispatch that paid its XLA
#: compile inside the device window (AOT and warm-jit dispatches never
#: emit it) — the cold-path attribution for trace critical paths.
SERVE_OPTIONAL_SPANS = ("compile",)

#: Train-side span names (docs/observability.md "Tracing").
TRAIN_SPANS = (
    "epoch",
    "data_iter",
    "step",
    "host_to_device",
    "step_dispatch",
    "telemetry_drain",
    "eval",
    "checkpoint_save",
)


@dataclasses.dataclass
class Span:
    """One closed host-side span. Times are raw ``clock()`` seconds;
    the Chrome export rebases them to the tracer's start."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float
    end: float
    tid: int
    args: dict | None = None
    #: Set inside a ``span()`` block to drop the span on exit (the
    #: timed_iter exhaustion probe is not a data pull).
    discard: bool = False

    @property
    def duration_ms(self) -> float:
        return (self.end - self.start) * 1e3


class Tracer:
    """Thread-safe span recorder with deterministic head sampling.

    ``clock`` is any monotonic ``() -> float`` (tests inject a fake);
    ``sample_rate`` in [0, 1] keeps that fraction of traces, decided at
    :meth:`start_trace` by a counter rule (trace ``n`` is kept iff
    ``floor(n * rate) > floor((n - 1) * rate)`` — rate 1.0 keeps all,
    0.25 keeps every 4th, 0 none; no RNG, so runs are replayable);
    ``max_spans`` bounds host memory (overflow increments ``dropped``,
    never blocks); ``annotate=True`` mirrors spans onto the jax
    profiler timeline (lazy import — only pay for it under
    ``--profile_dir``).

    ``recorder`` attaches an ``obs/dtrace.FlightRecorder``: every
    closed span is ALSO copied into its bounded ring, and sampled-OUT
    traces stop being invisible — :meth:`start_trace` hands them a
    shadow id (``"!"``-prefixed) whose spans go ONLY to the recorder,
    never the export buffer, so the trailing window is complete at any
    sample rate while the exported file keeps its sampling contract.
    """

    def __init__(
        self,
        *,
        path: str = "",
        sample_rate: float = 1.0,
        max_spans: int = 100_000,
        clock: Callable[[], float] = time.monotonic,
        annotate: bool = False,
        recorder=None,
    ):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in [0, 1], got {sample_rate}"
            )
        if max_spans < 1:
            raise ValueError(f"max_spans must be >= 1, got {max_spans}")
        self.path = path
        self.sample_rate = sample_rate
        self.max_spans = max_spans
        self._clock = clock
        self._annotate = annotate
        self._recorder = recorder
        self._t0 = clock()
        self._lock = threading.Lock()
        self._spans: list[Span] = []  #: guarded_by _lock
        self._dropped = 0  #: guarded_by _lock
        # Per-stream sampling counters (stream = trace-id prefix):
        # requests sample on "t", aux lifecycles (serve reloads) on
        # "r", so aux traces never shift which requests head sampling
        # keeps.
        self._stream_seen: dict[str, int] = {}  #: guarded_by _lock
        self._stream_kept: dict[str, int] = {}  #: guarded_by _lock
        # Adoption ledger (cluster propagation): unique trace ids this
        # tracer ADOPTED rather than decided, and how many of those
        # were sampled — per-host coverage honesty when the sampling
        # authority lives at the ClusterRouter. guarded_by _lock
        self._adopted_ids: set[str] = set()
        self._adopted_kept = 0
        self._next_span = 0  #: guarded_by _lock
        self._current: contextvars.ContextVar[Span | None] = (
            contextvars.ContextVar("gnot_trace_span", default=None)
        )

    # -- trace / span creation ---------------------------------------------

    def start_trace(self, stream: str = "t") -> str | None:
        """Head-sampling decision point: returns a fresh ``trace_id``
        when this trace is kept, ``None`` when sampled out. Callers
        thread the id (or the None) through the whole lifecycle — every
        downstream span call is a no-op for an unsampled trace.

        ``stream`` is the id prefix AND the sampling population:
        each stream counts (and floor-samples) independently, so e.g.
        serve reloads (stream ``"r"``) never consume a request keep
        slot — the documented request contract (rate 0.25 keeps
        requests 4, 8, 12, …) holds regardless of aux traffic.

        With a flight recorder attached, a sampled-OUT trace returns a
        SHADOW id (``"!"``-prefixed, from the seen counter so ids stay
        unique) instead of None: its spans record only into the
        recorder's ring — the export buffer, kept counters and the
        sampling contract are untouched."""
        with self._lock:
            n = self._stream_seen.get(stream, 0) + 1
            self._stream_seen[stream] = n
            keep = math.floor(n * self.sample_rate) > math.floor(
                (n - 1) * self.sample_rate
            )
            if not keep:
                if self._recorder is not None:
                    return f"!{stream}{n:06d}"
                return None
            kept = self._stream_kept.get(stream, 0) + 1
            self._stream_kept[stream] = kept
            return f"{stream}{kept:06d}"

    def adopt(self, ctx) -> str | None:
        """The receiving side of trace-context propagation
        (``obs/dtrace.TraceContext``): return the LOCAL trace id to
        thread through span sites for a propagated context, honoring
        the sender's sampling decision — this tracer's own counters
        are never consulted, so the head decision made once at the
        cluster holds identically on every host. A sampled context
        keeps its id verbatim; an unsampled one shadow-records when a
        flight recorder is attached (shadow prefix preserved across
        hops) and is a no-op (None) otherwise."""
        if ctx is None or not ctx.trace_id:
            return None
        tid = ctx.trace_id
        sampled = ctx.sampled and not tid.startswith("!")
        bare = tid.lstrip("!")
        with self._lock:
            # Unique-id ledger: a session's steps adopt the SAME ctx
            # once per step — one trace, one coverage unit.
            if bare not in self._adopted_ids:
                self._adopted_ids.add(bare)
                if sampled:
                    self._adopted_kept += 1
        if sampled:
            return tid
        if self._recorder is not None:
            return tid if tid.startswith("!") else f"!{tid}"
        return None

    def _new_span_id(self) -> str:
        with self._lock:
            self._next_span += 1
            return f"s{self._next_span:06d}"

    @contextlib.contextmanager
    def span(self, name: str, *, trace: str | None = None, args: dict | None = None):
        """Context-managed span. ``trace`` pins the trace id (root
        spans); omitted, it inherits the ambient (same-thread enclosing)
        span's trace. No ambient and no ``trace`` — or an unsampled
        ``trace=None`` — yields ``None`` and records nothing. The
        ambient span becomes the parent when it shares the trace id."""
        parent = self._current.get()
        trace_id = trace if trace is not None else (
            parent.trace_id if parent is not None else None
        )
        if trace_id is None:
            yield None
            return
        parent_id = (
            parent.span_id
            if parent is not None and parent.trace_id == trace_id
            else None
        )
        s = Span(
            name=name,
            trace_id=trace_id,
            span_id=self._new_span_id(),
            parent_id=parent_id,
            start=self._clock(),
            end=0.0,
            tid=threading.get_ident(),
            args=args,
        )
        token = self._current.set(s)
        ann = None
        if self._annotate:
            from gnot_tpu.utils import profiling

            ann = profiling.annotate(name)
            ann.__enter__()
        try:
            yield s
        finally:
            if ann is not None:
                ann.__exit__(None, None, None)
            self._current.reset(token)
            s.end = self._clock()
            if not s.discard:
                self._store(s)

    def add_span(
        self,
        name: str,
        start: float,
        end: float,
        *,
        trace: str | None,
        parent_id: str | None = None,
        tid: int | None = None,
        args: dict | None = None,
    ) -> str | None:
        """Record a span from timestamps measured elsewhere — the
        cross-thread phases (a request's queue-wait starts on the
        client thread and ends on the worker). Returns the span id, or
        None for an unsampled trace."""
        if trace is None:
            return None
        s = Span(
            name=name,
            trace_id=trace,
            span_id=self._new_span_id(),
            parent_id=parent_id,
            start=start,
            end=end,
            tid=tid if tid is not None else threading.get_ident(),
            args=args,
        )
        self._store(s)
        return s.span_id

    def timed_iter(
        self, it: Iterable, name: str, *, trace: str | None
    ) -> Iterator:
        """Wrap an iterator so each ``next()`` is recorded as one
        ``name`` span (the data-iteration phase: time the consumer
        spent WAITING on the producer, prefetch included). The final
        exhausted ``next()`` is discarded — N pulls export exactly N
        spans, so per-kind counts in trace_report match step counts."""
        it = iter(it)
        _end = object()
        while True:
            with self.span(name, trace=trace) as sp:
                item = next(it, _end)
                if item is _end and sp is not None:
                    sp.discard = True
            if item is _end:
                return
            yield item

    def _store(self, s: Span) -> None:
        if self._recorder is not None:
            # The black box sees EVERYTHING — sampled spans on their
            # way to the buffer and shadow spans of sampled-out traces.
            self._recorder.record_span(s)
        if s.trace_id.startswith("!"):
            return  # shadow: ring-only, never the export buffer
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(s)
            else:
                self._dropped += 1

    # -- inspection / export -----------------------------------------------

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def coverage(self) -> dict:
        """Honest sampling/coverage counters for summaries: traces
        seen vs kept (all streams), spans dropped to the buffer bound,
        and the configured rate — the numbers that stop a trace file
        from LOOKING complete when it is not (serve_summary /
        cluster_summary surface these)."""
        with self._lock:
            return {
                "seen": sum(self._stream_seen.values())
                + len(self._adopted_ids),
                "kept": sum(self._stream_kept.values())
                + self._adopted_kept,
                "adopted": len(self._adopted_ids),
                "dropped": self._dropped,
                "sample_rate": self.sample_rate,
            }

    def export(self) -> dict:
        """The buffered spans as a Chrome trace-event JSON object
        (``traceEvents`` of ``ph: "X"`` complete events, microsecond
        timestamps rebased to the earliest span start). Open the
        written file directly in ``chrome://tracing`` or
        https://ui.perfetto.dev — each OS thread renders as one track,
        span args (trace_id, bucket, step, ...) show on click."""
        with self._lock:
            spans = list(self._spans)
            dropped = self._dropped
            kept = sum(self._stream_kept.values())
            seen = sum(self._stream_seen.values())
        # Rebase against the earliest span, NOT the tracer's own clock
        # at construction: recorders stamp spans with their own
        # injectable clock (InferenceServer's queue-wait arithmetic
        # runs on the server clock), which need not share an epoch
        # with the tracer's — only offsets within the span set mean
        # anything.
        t0 = min((s.start for s in spans), default=self._t0)
        trace_events = [
            {
                "name": s.name,
                "cat": "host",
                "ph": "X",
                "ts": round((s.start - t0) * 1e6, 3),
                "dur": round((s.end - s.start) * 1e6, 3),
                "pid": os.getpid(),
                "tid": s.tid,
                "args": {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    **({"parent_id": s.parent_id} if s.parent_id else {}),
                    **(s.args or {}),
                },
            }
            for s in spans
        ]
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "generator": "gnot_tpu.obs.tracing",
                "sample_rate": self.sample_rate,
                "traces_seen": seen,
                "traces_kept": kept,
                "spans_dropped": dropped,
                # The rebase origin in this tracer's raw clock — what
                # obs/dtrace.merge_traces needs to map the rebased
                # timestamps back into an absolute clock frame before
                # applying a cross-host offset.
                "clock_t0_s": t0,
            },
        }

    def flush(self, sink=None) -> str | None:
        """Write the Chrome trace file to ``self.path`` (no-op without
        a path) and, given a sink, record a ``trace_flush`` event so
        the metrics stream names the artifact. Buffered spans are
        retained (flush is idempotent; the file is rewritten whole —
        Chrome JSON is one object, not appendable)."""
        if not self.path:
            return None
        out = self.export()
        if d := os.path.dirname(self.path):
            os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, self.path)
        if sink is not None:
            sink.log(
                event=events.TRACE_FLUSH,
                path=self.path,
                spans=len(out["traceEvents"]),
                dropped=out["otherData"]["spans_dropped"],
            )
        return self.path


def percentiles(values_ms: list[float]) -> dict:
    """p50/p99 of a duration list without numpy (stdlib-only module):
    nearest-rank on the sorted values. Empty -> Nones."""
    if not values_ms:
        return {"p50_ms": None, "p99_ms": None}
    v = sorted(values_ms)
    rank = lambda q: v[min(len(v) - 1, math.ceil(q * len(v)) - 1)]
    return {
        "p50_ms": round(rank(0.50), 4),
        "p99_ms": round(rank(0.99), 4),
    }
