"""THE central registry of MetricsSink event kinds.

Every ``event="..."`` record any module writes through the sink must be
declared here — name, required payload fields, emitting module — and
every entry here must be documented in docs/observability.md (serve
events also in docs/serving.md). The graftlint rule **GL005**
(``gnot_tpu/analysis/registry_drift.py``) enforces both directions in
tier-1, and ``tests/test_obs.py`` validates emitted payloads against
the specs, so a new event kind cannot ship undeclared, undocumented,
or under-populated.

Emit sites reference the module-level constants (``events.ROLLBACK``),
never fresh string literals — one rename touches one file. The module
is stdlib-only by design: the linter AST-parses it and the registry
must never pull jax into a bare ``tools/lint.py`` run.

The fault-kind counterpart lives in
``gnot_tpu/resilience/faults.py::FAULT_KINDS`` (documented in
docs/robustness.md, same GL005 enforcement).
"""

from __future__ import annotations

import dataclasses

# -- kind constants (the only spellings emit sites may use) ----------------

SLOW_STEP = "slow_step"
RECOMPILE = "recompile"
NON_FINITE_LOSS = "non_finite_loss"
HOST_SKEW = "host_skew"
ROLLBACK = "rollback"
BATCH_QUARANTINED = "batch_quarantined"
RECOVERY_RESTORE = "recovery_restore"
PREEMPT_SAVE = "preempt_save"
RESTORE = "restore"
RESTORE_FALLBACK = "restore_fallback"
IO_RETRY = "io_retry"
QUEUE_DEPTH = "queue_depth"
SHED = "shed"
BREAKER_OPEN = "breaker_open"
BREAKER_CLOSE = "breaker_close"
DRAIN_TIMEOUT = "drain_timeout"
RELOAD = "reload"
SERVE_SUMMARY = "serve_summary"
TRACE_FLUSH = "trace_flush"
ROUTE = "route"
REPLICA_HEALTH = "replica_health"
ROLLING_RELOAD = "rolling_reload"
AOT_PREWARM = "aot_prewarm"
REPLICA_WARM = "replica_warm"
NATIVE_PACKER = "native_packer"
ROLLOUT_STEP = "rollout_step"
SESSION_SNAPSHOT = "session_snapshot"
SESSION_MIGRATE = "session_migrate"
METRICS_SNAPSHOT = "metrics_snapshot"
SLO_ALERT = "slo_alert"
AUTOSCALE_DECISION = "autoscale_decision"
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
REPLICA_REMOVE = "replica_remove"
REPLICA_REPLACE = "replica_replace"
PROGRAM_CATALOG = "program_catalog"
CAPACITY_SNAPSHOT = "capacity_snapshot"
TENANT_QUOTA_SHED = "tenant_quota_shed"
HOST_HEARTBEAT = "host_heartbeat"
HOST_DEAD = "host_dead"
SESSION_REMIGRATE = "session_remigrate"
CLUSTER_SUMMARY = "cluster_summary"


@dataclasses.dataclass(frozen=True)
class EventSpec:
    """One event kind: the payload keys every record MUST carry (extra
    keys are always allowed — ``shed`` attaches per-reason detail, the
    ``recompile`` event dynamic ``compiles/<fn>`` counters), the
    DECLARED-optional keys (present only when the emitting feature is
    on — e.g. ``trace_id`` correlation keys exist only under
    ``--trace_path``; declaring them keeps the docs table honest
    without making tracing mandatory), the module that emits it, and
    the one-line description the docs table renders.
    """

    fields: tuple[str, ...]
    module: str
    doc: str
    optional: tuple[str, ...] = ()


#: kind -> spec. Keys are string literals ON PURPOSE: graftlint's GL005
#: reads this dict via ``ast`` without importing the package.
EVENTS: dict[str, EventSpec] = {
    "slow_step": EventSpec(
        fields=("step", "epoch", "step_time_s", "median_s", "slowdown"),
        module="gnot_tpu/obs/telemetry.py",
        doc="dispatch interval exceeded 3x the rolling median",
        optional=("span_id",),
    ),
    "recompile": EventSpec(
        fields=("epoch",),
        module="gnot_tpu/train/trainer.py",
        doc="a jitted step re-traced mid-run (shape leak); "
        "`compiles/<fn>` carry the per-fn deltas",
    ),
    "non_finite_loss": EventSpec(
        fields=("step", "epoch", "loss", "detail"),
        module="gnot_tpu/train/trainer.py",
        doc="NaN watchdog abort; `detail` is the checkify localization",
    ),
    "host_skew": EventSpec(
        fields=("epoch", "step_time_per_host", "skew_s"),
        module="gnot_tpu/train/trainer.py",
        doc="per-host epoch step-time gauge (multi-process runs)",
    ),
    "rollback": EventSpec(
        fields=("epoch", "step", "to_step", "rollbacks_used"),
        module="gnot_tpu/train/trainer.py",
        doc="recovery rolled back to the last-good snapshot",
    ),
    "batch_quarantined": EventSpec(
        fields=("epoch", "step", "ordinal"),
        module="gnot_tpu/train/trainer.py",
        doc="the offending dispatch is skipped on replay",
    ),
    "recovery_restore": EventSpec(
        fields=("epoch", "step", "restored_epoch", "restored_from"),
        module="gnot_tpu/train/trainer.py",
        doc="rollback budget exhausted; restored from checkpoint",
    ),
    "preempt_save": EventSpec(
        fields=("epoch", "step", "resumable"),
        module="gnot_tpu/train/trainer.py",
        doc="graceful SIGTERM/SIGINT stop saved `latest`",
    ),
    "restore": EventSpec(
        fields=(
            "requested", "name", "dir", "epoch", "best_metric", "fallback",
            "skipped",
        ),
        module="gnot_tpu/train/checkpoint.py",
        doc="clean (sidecar-named) checkpoint restore",
    ),
    "restore_fallback": EventSpec(
        fields=(
            "requested", "name", "dir", "epoch", "best_metric", "fallback",
            "skipped",
        ),
        module="gnot_tpu/train/checkpoint.py",
        doc="restore walked past corrupt/missing candidates",
    ),
    "io_retry": EventSpec(
        fields=("op", "attempt", "error"),
        module="gnot_tpu/train/checkpoint.py",
        doc="transient checkpoint-I/O failure retried with backoff",
    ),
    "queue_depth": EventSpec(
        fields=("depth", "batched", "dispatch", "bucket_nodes",
                "bucket_funcs", "n", "packed", "real_tokens",
                "capacity_tokens"),
        module="gnot_tpu/serve/server.py",
        doc="one serving dispatch (depth at flush, its bucket, and the "
        "dispatch's real-vs-capacity node tokens; `packed` marks a "
        "pack_plan dispatch)",
        optional=("trace_ids", "replica"),
    ),
    "shed": EventSpec(
        fields=("reason",),
        module="gnot_tpu/serve/server.py",
        doc="a request was shed/rejected (reason + per-reason detail; "
        "a shed rollout SESSION carries its `session` id; under a "
        "tenant policy the submitter's `tenant` tags the record)",
        optional=(
            "trace_id", "trace_ids", "replica", "session", "step",
            "tenant",
        ),
    ),
    "tenant_quota_shed": EventSpec(
        fields=("tenant", "quota", "in_system"),
        module="gnot_tpu/serve/server.py",
        doc="a request (or rollout step) fast-failed at the PER-TENANT "
        "admission quota (serve/policies.py TenantPolicy): the tenant's "
        "pool-wide in-system count was at its configured quota — shed "
        "at the door with reason `shed_tenant_quota`, sibling tenants "
        "unaffected; a quota-shed rollout step carries its `session` "
        "and is terminal (never migrated — the policy is pool-shared)",
        optional=("trace_id", "replica", "session"),
    ),
    "breaker_open": EventSpec(
        fields=("state", "reason", "detail", "trips"),
        module="gnot_tpu/serve/server.py",
        doc="circuit breaker tripped open (backend unhealthy)",
        optional=("trace_id", "replica"),
    ),
    "breaker_close": EventSpec(
        fields=("state",),
        module="gnot_tpu/serve/server.py",
        doc="half-open trial succeeded; breaker closed",
        optional=("replica",),
    ),
    "drain_timeout": EventSpec(
        fields=("timeout_s",),
        module="gnot_tpu/serve/server.py",
        doc="graceful drain exceeded its budget (wedged dispatch)",
        optional=("replica",),
    ),
    "reload": EventSpec(
        fields=("ok", "reload", "duration_ms"),
        module="gnot_tpu/serve/server.py",
        doc="hot weight reload (+ restore provenance when ok)",
        optional=("trace_id", "replica"),
    ),
    "serve_summary": EventSpec(
        fields=(
            "requests", "admitted", "completed", "shed", "dispatches",
            "reloads", "breaker_trips", "compiled_shapes",
            "latency_p50_ms", "latency_p99_ms",
        ),
        module="gnot_tpu/serve/server.py",
        doc="end-of-serve rollup emitted on drain (one per replica "
        "server plus one pool-level rollup from the router); `dtype` "
        "names the serving compute dtype the numbers were measured at",
        optional=(
            "queue_device_by_bucket", "pad_waste_by_bucket", "replica",
            "per_replica", "routing", "dtype", "sessions", "tenants",
            "trace",
        ),
    ),
    "route": EventSpec(
        fields=("replica", "bucket", "policy", "reason", "depth"),
        module="gnot_tpu/serve/router.py",
        doc="one placement decision: which replica got the request and "
        "why (affinity | cold_assign | spill | least_loaded | "
        "round_robin | pool_full | no_healthy); `dtype` is the pool's "
        "serving compute dtype; a rollout session's FIRST-step "
        "placement carries its `session` id (steps 2..K never "
        "re-route — session affinity)",
        optional=("dtype", "session"),
    ),
    "replica_health": EventSpec(
        fields=("replica", "healthy", "reason"),
        module="gnot_tpu/serve/router.py",
        doc="a replica's routability changed (ok | warming | "
        "breaker_open | wedged | dead); unhealthy replicas drain to "
        "siblings instead of shedding",
    ),
    "rolling_reload": EventSpec(
        fields=("replica", "ok", "step", "n_replicas", "rollout"),
        module="gnot_tpu/serve/router.py",
        doc="one step of a rolling hot-reload (one replica warming at "
        "a time; a failed step keeps old weights serving)",
    ),
    "aot_prewarm": EventSpec(
        fields=("replicas", "programs", "compile_s", "cache_dir"),
        module="gnot_tpu/serve/aot.py",
        doc="deploy-time AOT compile pass: the whole serving program "
        "family lowered + compiled into the persistent cache (and "
        "snapshotted) before any replica serves",
        optional=("snapshot_dir", "hits", "misses", "manifest",
                  "snapshot_bytes"),
    ),
    "replica_warm": EventSpec(
        fields=("replica", "source", "programs", "seconds"),
        module="gnot_tpu/serve/router.py",
        doc="one replica became serve-ready: `source` says how — "
        "'snapshot' (hydrated AOT executables, no compiles), "
        "'compile' (cold warmup dispatches), or 'none' (hydration "
        "refused; `reason` says why); emitted at pool prewarm "
        "and at every scale-out add_replica",
        optional=("hits", "misses", "reason"),
    ),
    "native_packer": EventSpec(
        fields=("available", "impl"),
        module="gnot_tpu/main.py",
        doc="one-time serve-start record of the host packer path: "
        "`impl` is 'native' (_ragged_pack.so loaded; dispatch is the "
        "payload-gated ADAPTIVE policy — the C fused pad/cast + "
        "batched unpad run above the recorded `*_min_bytes` bars, "
        "the bit-identical numpy fallback below them) or 'python' "
        "(fallback only; `error` says why), so bench artifacts are "
        "attributable to the code path that produced them",
        optional=("so", "error", "pack_native_min_bytes",
                  "unpad_native_min_bytes"),
    ),
    "rollout_step": EventSpec(
        fields=("session", "step", "steps", "latency_ms"),
        module="gnot_tpu/serve/server.py",
        doc="one committed step of an autoregressive rollout session "
        "(1-indexed `step` of `steps`; the carry advanced and the "
        "partial result streamed)",
        optional=("replica", "dispatch"),
    ),
    "session_snapshot": EventSpec(
        fields=("session", "step"),
        module="gnot_tpu/serve/server.py",
        doc="a rollout session's carry was snapshotted host-side (the "
        "rolling last-good state a migration replays from; cadence "
        "`serve.session_snapshot_every`, plus a final persist at "
        "drain; `persisted` marks a snapshot written to the on-disk "
        "session store — the state `resume_rollout` restarts from)",
        optional=("replica", "persisted"),
    ),
    "session_migrate": EventSpec(
        fields=(
            "session", "from_replica", "to_replica", "at_step",
            "replay_from", "reason",
        ),
        module="gnot_tpu/serve/router.py",
        doc="a rollout session was re-placed onto a sibling replica "
        "after its owner failed mid-rollout (`reason` names the "
        "failure; replay resumes from the `replay_from` snapshot "
        "cursor — at-least-once step semantics, zero lost sessions)",
    ),
    "metrics_snapshot": EventSpec(
        fields=("seq", "interval_s", "series", "pool"),
        module="gnot_tpu/obs/metrics.py",
        doc="one live metrics-plane publish cycle (obs/metrics.py, "
        "cadence `--metrics_interval_s`): `pool` is the cross-replica "
        "rollup (requests/completed/shed, merged-histogram p50/p99, "
        "queue depth) — the serve_summary numbers, live; the full "
        "per-series state goes to the JSONL time series and the "
        "Prometheus exposition file",
        optional=("series_path",),
    ),
    "slo_alert": EventSpec(
        fields=(
            "objective", "kind", "state", "threshold", "burn_fast",
            "burn_slow",
        ),
        module="gnot_tpu/obs/metrics.py",
        doc="an SLO objective crossed a burn-rate EDGE: `state` is "
        "'fire' (burn >= 1 in BOTH the fast and slow windows) or "
        "'clear' (the fast window recovered) — never level-triggered "
        "spam; `value` carries the observed quantity; a tenant-scoped "
        "objective (`latency_p99:<tenant>`) carries the `tenant` "
        "burning the budget — the autoscaler's attribution signal",
        optional=("value", "fast_window_s", "slow_window_s", "tenant"),
    ),
    "autoscale_decision": EventSpec(
        fields=("action", "reason", "pool", "min", "max"),
        module="gnot_tpu/serve/autoscaler.py",
        doc="the autoscaling controller acted (or was vetoed by a "
        "stability guard): `action` is 'scale_up' | 'scale_down' | "
        "'replace' | 'hold'; a 'hold' names the guard that vetoed a "
        "wanted move (cooldown_up | cooldown_down | cooldown_heal | "
        "at_max | flap_suppressed | last_replica | batch_deferral — "
        "pressure owned entirely by batch-class tenants is answered "
        "by deferral, not replicas) and is emitted on "
        "EDGES only (a steady veto stays silent); `load` is the "
        "per-replica in-system load the decision read, `alerts` the "
        "active SLO objectives",
        optional=("replica", "load", "alerts", "detail"),
    ),
    "scale_up": EventSpec(
        fields=("replica", "pool", "reason", "warm_source", "seconds"),
        module="gnot_tpu/serve/autoscaler.py",
        doc="the controller grew the pool: a new replica was built, "
        "warmed BEFORE joining (`warm_source` 'snapshot' = hydrated "
        "from the AOT manifest, 'compile' = cold warmup), and admitted "
        "to routing; `seconds` is build+warm+join, `reason` names the "
        "pressure signal (load | slo:<objective>)",
        optional=("load",),
    ),
    "scale_down": EventSpec(
        fields=("replica", "pool", "reason"),
        module="gnot_tpu/serve/autoscaler.py",
        doc="the controller shrank the pool: the named replica was "
        "retired via drain-then-remove (placement stopped, resident "
        "rollout sessions migrated to siblings, queued work flushed) "
        "after the calm held for the configured consecutive ticks",
        optional=("load", "sessions_migrated"),
    ),
    "replica_remove": EventSpec(
        fields=("replica", "reason", "requests", "completed"),
        module="gnot_tpu/serve/router.py",
        doc="one replica left the pool (scale-in or self-healing "
        "replacement): drain-then-remove finished — new placement "
        "stopped ('retiring' health state), resident sessions handed "
        "to siblings at a step boundary, its queue flushed, and its "
        "latency histograms retained in the pool rollup so the final "
        "serve_summary percentiles keep the retired replica's history",
        optional=("pool", "sessions_migrated", "drain_timeout_s"),
    ),
    "replica_replace": EventSpec(
        fields=("from_replica", "to_replica", "reason"),
        module="gnot_tpu/serve/autoscaler.py",
        doc="self-healing: a dead/wedged/breaker-stuck replica was "
        "removed and a fresh replacement built+warmed onto its device "
        "slot (`reason` is the health verdict that condemned it)",
        optional=("pool", "seconds"),
    ),
    "trace_flush": EventSpec(
        fields=("path", "spans", "dropped"),
        module="gnot_tpu/obs/tracing.py",
        doc="the span tracer wrote its Chrome trace-event JSON file",
    ),
    "program_catalog": EventSpec(
        fields=("key", "source"),
        module="gnot_tpu/serve/catalog.py",
        doc="a compiled program entered the catalog (serve/catalog.py): "
        "`key` is the dtype-keyed program signature (the AOT table's "
        "own name), `source` its provenance ('compile' = captured at "
        "first jit compile, 'hydrate' = live cost probe of a "
        "deserialized AOT executable, 'manifest' = costs carried in "
        "the prewarm manifest), and `costs` the XLA "
        "cost_analysis/memory_analysis dict (obs/costs.py; absent "
        "fields listed under `unavailable` — partial data degrades "
        "explicitly, never silently)",
        optional=("costs", "replica"),
    ),
    "host_heartbeat": EventSpec(
        fields=("host", "seq", "state"),
        module="gnot_tpu/serve/federation.py",
        doc="one failure-detector verdict per heartbeat round per host "
        "(federated serving, docs/distributed.md): `state` is 'alive' "
        "| 'suspect' | 'dead' — the lease view AFTER this round's "
        "ack/silence was folded in; `load` and `pool` carry the "
        "host's reported in-system load and replica count when the "
        "ack arrived, `rtt_ms` the heartbeat round-trip; "
        "`clock_offset_s` ± `clock_err_s` is the midpoint-method "
        "monotonic-clock alignment estimate obs/dtrace.py derives "
        "from the stamped heartbeat exchanges (the cross-host span "
        "rebase the merged trace uses)",
        optional=(
            "load", "pool", "rtt_ms", "edge", "clock_offset_s",
            "clock_err_s",
        ),
    ),
    "host_dead": EventSpec(
        fields=("host", "silent_s", "sessions"),
        module="gnot_tpu/serve/federation.py",
        doc="the failure detector declared a host dead after the full "
        "suspicion dwell (`silent_s` of lease silence): its pending "
        "requests are re-placed on survivors and its `sessions` "
        "resident rollout sessions re-migrate from their persisted "
        "snapshots (docs/distributed.md 'Failure detector')",
        optional=("pending", "reason"),
    ),
    "session_remigrate": EventSpec(
        fields=(
            "session", "from_host", "to_host", "at_step", "replay_from",
            "reason",
        ),
        module="gnot_tpu/serve/federation.py",
        doc="a rollout session was re-placed onto a SURVIVING HOST "
        "after its owner host died or partitioned away mid-trajectory "
        "— the cross-host analogue of `session_migrate`: replay "
        "resumes from the `replay_from` cursor of the persisted "
        "SessionStore snapshot (0 = no snapshot survived, full "
        "at-least-once replay; re-delivered steps are suppressed at "
        "the cluster layer)",
    ),
    "cluster_summary": EventSpec(
        fields=(
            "hosts", "requests", "completed", "shed", "sessions",
            "remigrated", "hosts_dead",
        ),
        module="gnot_tpu/serve/federation.py",
        doc="end-of-federation rollup emitted once at cluster drain "
        "(beside each host's own `serve_summary`): cluster-level "
        "request/session accounting, the per-host breakdown "
        "(`per_host`), and the failure-detector ledger — the "
        "cross-check target for tools/metrics_report.py's per-host "
        "slicing; with cluster tracing on, `trace_coverage` carries "
        "per-host sampled/total counters, dropped-span counts and the "
        "clock-offset ± uncertainty each host's spans were rebased by",
        optional=("per_host", "lost", "protocol_errors",
                  "trace_coverage"),
    ),
    "capacity_snapshot": EventSpec(
        fields=("programs", "pool"),
        module="gnot_tpu/serve/catalog.py",
        doc="drain-time capacity model: per-program cost x traffic "
        "rates (device-time per token, achieved FLOPs/s, useful-token "
        "fraction) and the pool rollup of sustainable tokens/s and "
        "requests/s per replica (x / device_s — the 100%-device-duty "
        "bound tools/capacity_report.py compares offered load "
        "against); retired replicas' traffic is merged in",
        optional=("replica",),
    ),
}

# A constant and a dict key drifting apart would defeat the registry;
# cheap to assert once at import (stdlib only, no jax in the loop).
# (Span kinds deliberately have NO module constants — span sites pass
# the name to the tracer as a literal, which is what GL005 resolves —
# so the constant sweep below sees only event kinds.)
_CONSTANT_KINDS = {
    v for k, v in vars().items() if k.isupper() and isinstance(v, str)
}
assert _CONSTANT_KINDS == set(EVENTS), (
    "obs/events.py constants and EVENTS keys drifted: "
    f"{sorted(_CONSTANT_KINDS ^ set(EVENTS))}"
)


@dataclasses.dataclass(frozen=True)
class SpanSpec:
    """One tracer span kind: the module that records it and the
    one-line description docs/observability.md renders. The span
    analogue of :class:`EventSpec` — GL005 resolves every literal
    span name at a ``Tracer`` call site against this dict and checks
    the docs row, so span names cannot drift the way event kinds
    already cannot."""

    module: str
    doc: str


#: span kind -> spec. Keys are string literals ON PURPOSE (GL005
#: AST-parses this dict without importing). The serve/train taxonomy
#: tuples in ``obs/tracing.py`` (SERVE_SPANS & co) stay the ordering
#: contract; this registry is the DRIFT GUARD — ``tests/test_obs.py``
#: pins the two views equal.
SPANS: dict[str, SpanSpec] = {
    "admission": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="admission decision at submit (`reason` = admitted or the "
        "shed/reject verdict); the root of every serve request chain",
    ),
    "queue_wait": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="admission close to dispatch pop — time spent queued "
        "(terminal rejects record it with the reject `reason`)",
    ),
    "batch_assembly": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="pad/pack of the dispatch's batch, once per traced member",
    ),
    "dispatch": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="the whole engine dispatch window (queue pop to result "
        "publishable); `member_trace_ids` links co-dispatched riders",
    ),
    "device": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="device execution inside the dispatch (engine phase stamp)",
    ),
    "unpad": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="host-side unpad/scatter of the batch outputs",
    ),
    "resolve": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="result resolution (`reason`, `latency_ms`) — the chain's "
        "terminal span",
    ),
    "compile": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="fresh-signature jit dispatch paid its XLA compile inside "
        "the device window (AOT / warm-jit dispatches never emit it)",
    ),
    "reload": SpanSpec(
        module="gnot_tpu/serve/server.py",
        doc="hot weight reload lifecycle (aux stream `r` — never "
        "consumes a request sampling slot)",
    ),
    "replica_warm": SpanSpec(
        module="gnot_tpu/serve/router.py",
        doc="one replica's warm-to-serve-ready window (snapshot "
        "hydration or cold compile; aux stream `r`)",
    ),
    "epoch": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="one training epoch — the root of each train trace",
    ),
    "data_iter": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="one batch pull from the input pipeline (prefetch wait)",
    ),
    "step": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="one optimizer step (host view)",
    ),
    "host_to_device": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="device_put of the step's batch",
    ),
    "step_dispatch": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="the jitted step dispatch inside `step`",
    ),
    "telemetry_drain": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="end-of-epoch telemetry queue drain",
    ),
    "eval": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="held-out evaluation pass",
    ),
    "checkpoint_save": SpanSpec(
        module="gnot_tpu/train/trainer.py",
        doc="checkpoint write (atomic tmp+rename)",
    ),
    "placement": SpanSpec(
        module="gnot_tpu/serve/federation.py",
        doc="one controller→host placement frame of a cluster request "
        "or session (`host`, `kind` = place | hedge | redeliver | "
        "remigrate | reconcile | restart; non-place kinds carry "
        "`link_to` = the first placement's span id — hedged "
        "duplicates, age-based re-deliveries and re-migrations appear "
        "as LINKED spans of the same trace, never a second chain)",
    ),
    "cluster_request": SpanSpec(
        module="gnot_tpu/serve/federation.py",
        doc="one one-shot's whole cluster lifecycle, submit to "
        "resolution (`reason`; recorded at resolve on the controller)",
    ),
    "cluster_rollout": SpanSpec(
        module="gnot_tpu/serve/federation.py",
        doc="one rollout session's whole cluster lifecycle, placement "
        "to terminal resolution (`reason`, `migrations`)",
    ),
}


def spans_markdown_table() -> str:
    """The docs/observability.md span table, generated from ``SPANS``
    the same way :func:`markdown_table` renders ``EVENTS``."""
    lines = [
        "| span | recorded by | meaning |",
        "|---|---|---|",
    ]
    for kind, spec in SPANS.items():
        lines.append(f"| `{kind}` | `{spec.module}` | {spec.doc} |")
    return "\n".join(lines)


def validate_record(record: dict) -> list[str]:
    """Missing-field / unknown-kind problems for one sink record (empty
    list = valid). Non-event records (no ``event`` key — step/epoch
    metrics) always validate."""
    kind = record.get("event")
    if kind is None:
        return []
    spec = EVENTS.get(kind)
    if spec is None:
        return [f"unknown event kind {kind!r}"]
    return [
        f"event {kind!r} missing required field {f!r}"
        for f in spec.fields
        if f not in record
    ]


def markdown_table() -> str:
    """The docs/observability.md event table, generated from the
    registry so the docs cannot drift from the code (GL005 checks the
    reverse direction — every kind mentioned in the doc)."""
    lines = [
        "| event | required fields | optional fields | emitted by | meaning |",
        "|---|---|---|---|---|",
    ]
    for kind, spec in EVENTS.items():
        fields = ", ".join(f"`{f}`" for f in spec.fields)
        opt = ", ".join(f"`{f}`" for f in spec.optional) or "—"
        lines.append(
            f"| `{kind}` | {fields} | {opt} | `{spec.module}` | {spec.doc} |"
        )
    return "\n".join(lines)
