"""Training health monitors: recompile detection, slow-step outliers,
NaN localization.

All three are host-side and drain-cadence — they read what the
telemetry buffer already fetched (losses, dispatch wall-times) or cheap
host counters (jit cache sizes), so none of them adds device syncs to
the hot path. Semantics are documented in docs/observability.md.
"""

from __future__ import annotations

import logging
from typing import Callable

logger = logging.getLogger(__name__)


class RecompileMonitor:
    """Trace-counter deltas over registered jitted callables.

    ``jax.jit`` wrappers expose ``_cache_size()`` — the number of
    distinct (shape, dtype, static-arg) specializations compiled so
    far. The first ``check()`` snapshots the warm-up compiles as the
    baseline; any later positive delta is a RECOMPILE (a shape leak —
    e.g. unbucketed lengths, an LR passed as a Python float) and is the
    "silent recompile storm" signal the per-epoch prints can't see.
    On a jax without the counter the monitor degrades to no-op.
    """

    def __init__(self) -> None:
        self._fns: dict[str, Callable] = {}
        self._last: dict[str, int] = {}
        self._baselined = False

    def register(self, name: str, fn) -> None:
        if fn is not None and callable(getattr(fn, "_cache_size", None)):
            self._fns[name] = fn

    def _sizes(self) -> dict[str, int]:
        out = {}
        for name, fn in self._fns.items():
            try:
                out[name] = int(fn._cache_size())
            except Exception:  # counter went away mid-run; drop the fn
                continue
        return out

    def check(self) -> dict[str, int]:
        """Per-fn compile-count deltas since the previous check. The
        first call records the baseline (initial traces) and returns
        ``{}``; later calls return only fns that recompiled."""
        sizes = self._sizes()
        if not self._baselined:
            self._last = sizes
            self._baselined = True
            return {}
        deltas = {
            name: n - self._last.get(name, 0)
            for name, n in sizes.items()
            if n > self._last.get(name, 0)
        }
        self._last = sizes
        return deltas


class SlowStepMonitor:
    """Dispatch-interval outlier gauge.

    Observes the host wall-time between step dispatches (measured by
    the telemetry buffer). On the async dispatch path this interval is
    near-zero until the device queue backpressures, so a spike means a
    host-side stall: a recompile blocking dispatch, a straggling
    collective, input-pipeline starvation. An observation counts as an
    outlier when it exceeds ``factor`` x the rolling median of the last
    ``window`` observations, after ``warmup`` observations have
    seeded the median (compile steps land in the warmup).
    """

    def __init__(self, factor: float = 3.0, warmup: int = 10, window: int = 256):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        self.factor = factor
        self.warmup = warmup
        self.window = window
        self._times: list[float] = []
        self._seen = 0

    def observe(self, dt: float) -> dict | None:
        """Feed one dispatch interval (seconds); returns the outlier
        record (``step_time_s``/``median_s``/``slowdown``) or None."""
        self._seen += 1
        out = None
        if self._seen > self.warmup and len(self._times) >= 2:
            import statistics

            med = statistics.median(self._times)
            if med > 0 and dt > self.factor * med:
                out = {
                    "step_time_s": dt,
                    "median_s": med,
                    "slowdown": dt / med,
                }
        self._times.append(dt)
        if len(self._times) > self.window:
            del self._times[: len(self._times) - self.window]
        return out


def localize_nan(loss_fn, params, batch) -> str | None:
    """Re-execute ``loss_fn(params, batch)`` under
    ``utils.debug.checked`` to name the op that produced the first
    NaN/inf. Returns checkify's report (op + source location) or None
    when the re-run comes back clean — a NON-reproducing NaN, which
    with the post-update ``params`` the trainer passes means the bad
    value came from the state the offending step already consumed (the
    watchdog fires one drain window after the fact, by design: the hot
    path carries no per-step syncs)."""
    from jax.experimental import checkify

    from gnot_tpu.utils.debug import checked

    guarded = checked(loss_fn)
    try:
        guarded(params, batch)
    except checkify.JaxRuntimeError as exc:
        return str(exc)
    return None
