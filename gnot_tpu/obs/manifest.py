"""Run manifest: one ``run.json`` of provenance per run.

Answers "what exactly produced this metrics file" without re-deriving it
from shell history: config snapshot, git revision, library versions,
device topology, mesh shape, and persistent compile-cache stats. Written
at startup (before training can crash) by ``main.py`` next to the
``--metrics_path`` JSONL, and by ``bench.py --metrics_path`` — one
report tool reads both.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import time
from typing import Any


def _git_rev() -> dict:
    """Best-effort git provenance of the installed package tree; a
    non-repo install (wheel, bare container) reports nulls, never
    raises."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    out = {"rev": None, "dirty": None}
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=10,
        )
        if rev.returncode == 0:
            out["rev"] = rev.stdout.strip()
            status = subprocess.run(
                ["git", "status", "--porcelain"], cwd=root,
                capture_output=True, text=True, timeout=10,
            )
            if status.returncode == 0:
                out["dirty"] = bool(status.stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    return out


def _versions() -> dict:
    vers = {}
    for name in ("jax", "jaxlib", "flax", "optax", "numpy", "orbax.checkpoint"):
        try:
            mod = __import__(name)
            for part in name.split(".")[1:]:
                mod = getattr(mod, part)
            vers[name] = getattr(mod, "__version__", None)
        except ImportError:
            vers[name] = None
    return vers


def _devices() -> dict:
    import jax

    devices = jax.devices()
    return {
        "platform": devices[0].platform if devices else None,
        "device_kind": getattr(devices[0], "device_kind", None) if devices else None,
        "n_devices": len(devices),
        "n_local_devices": jax.local_device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
    }


def _compile_cache_stats() -> dict:
    """Size/entry count of the persistent XLA compile cache
    (utils/cache.py enables it by default): a near-empty cache on a
    supposedly warm host explains a slow first epoch; entry-count
    growth across runs is the compile-churn signal."""
    import jax

    path = getattr(jax.config, "jax_compilation_cache_dir", None)
    stats = {"dir": path, "entries": None, "bytes": None}
    if path and os.path.isdir(path):
        entries = n_bytes = 0
        try:
            for de in os.scandir(path):
                if de.is_file():
                    entries += 1
                    n_bytes += de.stat().st_size
            stats["entries"], stats["bytes"] = entries, n_bytes
        except OSError:
            pass
    return stats


def _snapshot(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def build_manifest(
    *,
    config: Any = None,
    model_config: Any = None,
    mesh=None,
    argv=None,
    extra: dict | None = None,
) -> dict:
    manifest = {
        "ts": time.time(),
        "argv": list(argv) if argv is not None else None,
        "config": _snapshot(config),
        "model_config": _snapshot(model_config),
        "git": _git_rev(),
        "versions": _versions(),
        "devices": _devices(),
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "compile_cache": _compile_cache_stats(),
    }
    if extra:
        manifest.update(extra)
    return manifest


def write_manifest(path: str, **kwargs) -> dict:
    """Build and atomically write the manifest (tmp + rename: a reader
    polling the run dir never sees a torn file). Returns the dict."""
    manifest = build_manifest(**kwargs)
    if d := os.path.dirname(path):
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return manifest


def manifest_path_for(metrics_path: str) -> str:
    """The manifest lives next to the metrics JSONL as ``run.json`` —
    unless a DIFFERENT run's ``run.json`` is already there (two runs
    sharing a directory, e.g. a bench alongside a training run), in
    which case it falls back to ``<metrics-stem>.run.json`` so the
    first run's provenance is not clobbered."""
    metrics_path = os.path.abspath(metrics_path)
    default = os.path.join(os.path.dirname(metrics_path), "run.json")
    try:
        with open(default) as f:
            existing = json.load(f)
    except (OSError, json.JSONDecodeError):
        return default  # absent or torn: ours to (re)write
    if os.path.abspath(existing.get("metrics_path") or "") == metrics_path:
        return default  # a re-run of the same metrics file
    stem = os.path.splitext(os.path.basename(metrics_path))[0]
    return os.path.join(os.path.dirname(metrics_path), f"{stem}.run.json")
