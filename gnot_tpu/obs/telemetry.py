"""On-device scalar telemetry for the jitted train step.

Design constraint (docs/observability.md): telemetry must not add host
syncs to the hot path. Everything here is therefore computed INSIDE the
already-compiled step — the norms reduce values the backward pass
materializes anyway, the gate stats ride the forward as sown
intermediates — and returned as a second output the trainer buffers as
device arrays. The host fetches one whole drain window at a time
(``TelemetryBuffer``), so the per-step cost is a handful of fused
reductions plus one deferred tiny transfer per ``log_every`` steps.

Step builders mirror the trainer's (single-device, K-step scanned,
GSPMD-sharded); each returns ``(state, (loss, telem))`` where ``telem``
is a flat dict of f32 scalars plus ``[n_expert]`` gate-load vectors.
With the standard forward the gate stats are captured per block via the
``intermediates`` collection (models/gnot.py sows them); overridden
forwards (flat/packed/stacked loss_fn) keep their own loss math and get
the norm/padding telemetry only — the mutable-apply capture does not
reach through their custom apply paths.
"""

from __future__ import annotations

import math
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gnot_tpu.obs import events
from gnot_tpu.ops.segment import LOSSES


def telemetry_loss_fn(model, loss_name: str) -> Callable:
    """Standard masked/parity forward + loss, with the model's sown
    ``intermediates`` (per-block gate stats) returned as aux."""

    def loss_fn(params, batch):
        preds, mut = model.apply(
            {"params": params},
            batch.coords,
            batch.theta,
            batch.funcs,
            node_mask=batch.node_mask,
            func_mask=batch.func_mask,
            mutable=["intermediates"],
        )
        loss = LOSSES[loss_name](preds, batch.y, batch.node_mask)
        return loss, mut.get("intermediates", {})

    return loss_fn


def instrument(aux, grads, updates, params, batch) -> dict:
    """The train_step_body telemetry hook: device-side reductions over
    values the step already holds. ``params`` is the POST-update tree
    (param-norm tracks where the model is, not where it was)."""
    telem = {
        "grad_norm": optax.global_norm(grads),
        "update_norm": optax.global_norm(updates),
        "param_norm": optax.global_norm(params),
    }
    mask = getattr(batch, "node_mask", None)
    if mask is not None:
        telem["padding_waste"] = 1.0 - jnp.mean(mask.astype(jnp.float32))
    if aux:
        # intermediates tree: {block_i: {gate_load: (v,), gate_entropy: (v,)}}
        # (flax sow appends into tuples). Flatten to "gate_load/block_i".
        for block, stats in aux.items():
            for key, v in stats.items():
                telem[f"{key}/{block}"] = v[0] if isinstance(v, tuple) else v
    return telem


def _telemetry_body(model, optim_cfg, loss_name: str, loss_fn):
    from gnot_tpu.train.trainer import train_step_body

    if loss_fn is None:
        return train_step_body(
            model, optim_cfg, loss_name,
            loss_fn=telemetry_loss_fn(model, loss_name),
            instrument=instrument, loss_has_aux=True,
        )
    # Overridden forward (flat / packed / stacked): its loss math stays
    # untouched; telemetry degrades to the norm/padding scalars.
    return train_step_body(
        model, optim_cfg, loss_name, loss_fn=loss_fn, instrument=instrument
    )


def make_train_step(model, optim_cfg, loss_name: str, *, loss_fn=None) -> Callable:
    import functools

    body = _telemetry_body(model, optim_cfg, loss_name, loss_fn)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, batch, lr):
        return body(state, (batch, lr))

    return train_step


def make_multi_train_step(model, optim_cfg, loss_name: str, *, loss_fn=None) -> Callable:
    """K-step scanned telemetry step: ys stack to ``(loss[K], telem[K])``
    — the scan body is the same instrumented train_step_body."""
    import functools

    body = _telemetry_body(model, optim_cfg, loss_name, loss_fn)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def multi_step(state, batches, lrs):
        return jax.lax.scan(body, state, (batches, lrs))

    return multi_step


#: The one copy of the pipeline-rejection message (Trainer.__init__
#: raises it early from config, the sharded builders from the mesh).
PIPE_ERROR = (
    "telemetry does not compose with the pipeline mesh path yet (the "
    "shard_map schedule builds its own step); set mesh pipe=1 or "
    "disable telemetry"
)


def _reject_pipe(mesh) -> None:
    if mesh.shape.get("pipe", 1) > 1:
        raise ValueError(PIPE_ERROR)


def make_sharded_train_step(
    model, optim_cfg, loss_name: str, mesh, state, microbatches: int = 0,
    loss_fn=None,
) -> Callable:
    """GSPMD telemetry step: the telemetry outputs come back replicated
    (they are full reductions, so XLA's psums make them globally-reduced
    on every host — multi-host aggregation by construction). Signature
    mirrors ``mesh.make_sharded_train_step`` so the trainer selects the
    builder with one conditional; ``microbatches`` only ever routed to
    the pipeline path, which telemetry rejects."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gnot_tpu.parallel import mesh as mesh_lib

    _reject_pipe(mesh)
    mesh_lib._validate_gspmd(model, mesh)
    body = _telemetry_body(model, optim_cfg, loss_name, loss_fn)
    st_sh = mesh_lib.state_shardings(mesh, state)
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        lambda state, batch, lr: body(state, (batch, lr)),
        in_shardings=(st_sh, None, replicated),
        out_shardings=(st_sh, replicated),  # prefix: (loss, telem) replicate
        donate_argnums=(0,),
    )


def make_sharded_multi_train_step(
    model, optim_cfg, loss_name: str, mesh, state, *, loss_fn=None
) -> Callable:
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gnot_tpu.parallel import mesh as mesh_lib

    _reject_pipe(mesh)
    mesh_lib._validate_gspmd(model, mesh)
    body = _telemetry_body(model, optim_cfg, loss_name, loss_fn)
    st_sh = mesh_lib.state_shardings(mesh, state)
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        lambda state, batches, lrs: jax.lax.scan(body, state, (batches, lrs)),
        in_shardings=(st_sh, None, replicated),
        out_shardings=(st_sh, replicated),
        donate_argnums=(0,),
    )


class TelemetryBuffer:
    """Device-resident telemetry accumulator with batched drains.

    ``append`` stores the step's ``(loss, telem)`` DEVICE arrays plus
    host bookkeeping (steps, lrs, dispatch wall-time, batch refs) — no
    transfer, no sync. ``drain`` fetches the whole window in one
    ``jax.device_get``, runs the health hooks (slow-step gauge, NaN
    watchdog) over every step, and writes one JSONL record per
    ``log_every``-multiple step to the sink. The trainer drains on the
    window boundary and at epoch end, so at ``log_every=10`` the hot
    path sees one deferred fetch of ~10 tiny arrays per 10 steps.

    ``sink=None`` (non-zero processes of a multi-host run) keeps the
    health checks without writing records. ``on_nonfinite(step, epoch,
    loss, batch)`` fires at most once, on the FIRST non-finite loss in
    a drained window (the NaN watchdog — it raises, ending the run).
    ``keep_batches=False`` drops the batch refs (multi-process runs,
    where the watchdog skips the localization re-run anyway — no point
    pinning a window of padded batches in host RAM).

    ``metrics`` (an ``obs.metrics.MetricsRegistry``, optional) is the
    live metrics plane's train-side tap: every drained dispatch
    interval lands in the ``train_step_time_ms`` windowed histogram and
    every slow-step outlier bumps ``train_slow_steps_total`` — the same
    registry/publisher machinery the serving tier streams through, at
    drain cadence (no new host syncs on the hot path).
    """

    #: drain cadence when log_every is 0 (telemetry on, records off —
    #: health monitors still need periodic loss visibility).
    DEFAULT_DRAIN = 50

    def __init__(
        self, sink, log_every: int, *, slow_step=None, on_nonfinite=None,
        keep_batches: bool = True, metrics=None,
    ):
        self.sink = sink
        self.record_every = max(0, int(log_every))
        self.drain_every = self.record_every or self.DEFAULT_DRAIN
        self.keep_batches = keep_batches
        self._entries: list[dict] = []
        self._pending_steps = 0
        self._slow = slow_step
        self._on_nonfinite = on_nonfinite
        self._last_t: float | None = None
        self._step_hist = (
            metrics.histogram("train_step_time_ms")
            if metrics is not None
            else None
        )
        self._slow_counter = (
            metrics.counter("train_slow_steps_total")
            if metrics is not None
            else None
        )

    def append(
        self, *, steps, epoch, lrs, loss, telem, batches, span_ids=None
    ) -> None:
        """One dispatch's outputs: ``steps``/``lrs``/``batches`` are
        length-K lists (K=1 single step), ``loss``/``telem`` the device
        outputs (stacked on a leading K axis for K > 1). ``span_ids``
        (optional, parallel to ``steps``) are the tracer span ids of
        the dispatches — a ``slow_step`` outlier event then names the
        span it indicts, so the alert points into the trace file."""
        now = time.perf_counter()
        dt = (now - self._last_t) / len(steps) if self._last_t is not None else None
        self._last_t = now
        if not self.keep_batches:
            batches = [None] * len(steps)
        self._entries.append(
            dict(steps=list(steps), epoch=epoch, lrs=list(lrs), loss=loss,
                 telem=telem, batches=list(batches), dt=dt,
                 span_ids=list(span_ids) if span_ids is not None else None)
        )
        self._pending_steps += len(steps)
        if self._pending_steps >= self.drain_every:
            self.drain()

    def discard(self) -> None:
        """Drop the buffered window WITHOUT draining (recovery rollback,
        resilience/supervisor.py): the rolled-back steps' records would
        be bogus, and the non-finite loss buried in them must not
        re-fire the watchdog on the next drain."""
        self._entries.clear()
        self._pending_steps = 0
        self._last_t = None

    def drain(self) -> None:
        if not self._entries:
            return
        entries, self._entries = self._entries, []
        self._pending_steps = 0
        # Reset the dispatch-interval clock: whatever happens between a
        # drain and the next append (the epoch-end eval/checkpoint pass
        # after the trainer's flush — or this drain's own fetch+writes)
        # is not a step interval, and timing it would hand the slow-step
        # monitor a guaranteed false outlier every epoch.
        self._last_t = None
        fetched = jax.device_get([(e["loss"], e["telem"]) for e in entries])
        for e, (loss, telem) in zip(entries, fetched):
            k = len(e["steps"])
            if self._step_hist is not None and e["dt"] is not None:
                self._step_hist.record(e["dt"] * 1e3)
            if self._slow is not None and e["dt"] is not None:
                outlier = self._slow.observe(e["dt"])
                if outlier is not None and self._slow_counter is not None:
                    self._slow_counter.inc()
                if outlier is not None and self.sink is not None:
                    ids = e.get("span_ids") or []
                    span_id = next((s for s in ids if s is not None), None)
                    self.sink.log(
                        event=events.SLOW_STEP, step=e["steps"][-1],
                        epoch=e["epoch"], **outlier,
                        **({"span_id": span_id} if span_id else {}),
                    )
            loss = np.atleast_1d(np.asarray(loss))
            for i, step in enumerate(e["steps"]):
                li = float(loss[i] if k > 1 else loss[0])
                if (
                    self.sink is not None
                    and self.record_every
                    and step % self.record_every == 0
                ):
                    rec = {"step": step, "epoch": e["epoch"], "loss": li,
                           "lr": e["lrs"][i]}
                    for key, v in telem.items():
                        arr = np.asarray(v)
                        rec[key] = arr[i] if k > 1 else arr
                    self.sink.log(**rec)
                if not math.isfinite(li) and self._on_nonfinite is not None:
                    # Records up to and including the bad step are
                    # already written; the watchdog raises.
                    self._on_nonfinite(step, e["epoch"], li, e["batches"][i])
