"""Cluster-scoped distributed tracing: context propagation, clock
alignment, cross-host stitching, and the anomaly flight recorder.

The PR 5 tracer (``obs/tracing.py``) is strictly per-process: the
moment a request crosses the federation wire (``serve/federation.py``
placement, hedge, re-delivery, session re-migration) its causal chain
is severed, and every per-host ``Tracer`` runs on its own monotonic
clock so cross-host spans cannot even be ORDERED. This module supplies
the four missing pieces (docs/observability.md "Distributed tracing"):

* :class:`TraceContext` — the wire form of one sampling decision
  (trace id, parent span id, sampled flag, tenant), carried as the
  optional ``trace_ctx`` field on every request-bearing ``MESSAGES``
  kind. Head sampling is decided ONCE at the ``ClusterRouter`` and
  honored identically on every host: a host NEVER consults its own
  sampling counter for propagated work.
* :class:`ClockSync` — per-host monotonic-clock offset ± uncertainty
  estimated from the heartbeat request/ack round trips the federation
  already pays for (midpoint method over a sliding window, trusting
  the minimum-RTT exchange: ``offset = remote_t - (t_send+t_recv)/2``,
  uncertainty ``rtt/2`` — the honest bound; asymmetric paths can hide
  anywhere inside it, never outside it).
* :func:`merge_traces` — stitch per-host Chrome exports into ONE trace
  file: remote span times are rebased into the controller's clock by
  the estimated offsets, span/parent ids are host-prefixed so the
  per-host ``s%06d`` counters cannot collide, and every remote span
  gains a ``host`` arg (the per-host breakdown key in
  ``tools/trace_report.py``). Per-host offset ± uncertainty and
  coverage counters are recorded in ``otherData`` — a merged trace
  carries its own error bars.
* :class:`FlightRecorder` — head-sampling's blind spot turned into a
  postmortem artifact: a bounded ring buffer retaining ALL spans and
  events of the trailing ``window_s`` regardless of sample rate
  (unsampled traces record "shadow" spans that exist ONLY here — see
  ``Tracer.start_trace``), dumped atomically to JSON on trigger edges.
  :class:`FlightRecorderSink` wraps any MetricsSink and fires the dump
  on ``slo_alert`` FIRE, ``breaker_open``, ``host_dead`` and
  ``non_finite_loss`` records; ``watch_lockguard`` adds the
  ``utils/lockguard.py`` runtime deadlock witness as a trigger.

Stdlib-only by design (same constraint as ``obs/events.py``): the
federation imports this on every host and ``tools/lint.py`` must be
able to reason about it without jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable

#: Trace-id prefix marking a SHADOW trace: sampled OUT by head
#: sampling, recorded only into a flight recorder's ring (never the
#: tracer's export buffer). The prefix travels the wire, so a request
#: shadow-traced at the controller stays shadow on every host.
SHADOW_PREFIX = "!"


# --------------------------------------------------------------------------
# Trace-context propagation
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One head-sampling decision in wire form.

    ``trace_id`` is the cluster-assigned id (possibly shadow-prefixed);
    ``span_id`` the cluster-side parent span the receiving host should
    chain under; ``sampled`` the decision itself — False means "do not
    export spans for this request" (a host with a flight recorder still
    shadow-records them); ``tenant`` rides along so host-side spans are
    tenant-attributable without a second lookup.
    """

    trace_id: str
    span_id: str | None = None
    sampled: bool = True
    tenant: str | None = None

    def to_wire(self) -> dict:
        d: dict = {"trace_id": self.trace_id, "sampled": self.sampled}
        if self.span_id is not None:
            d["span_id"] = self.span_id
        if self.tenant is not None:
            d["tenant"] = self.tenant
        return d

    @staticmethod
    def from_wire(d: dict | None) -> "TraceContext | None":
        """Tolerant decode: a missing/malformed ``trace_ctx`` field is
        None (the request simply runs untraced) — a peer speaking a
        newer dialect can never wedge admission."""
        if not isinstance(d, dict) or not d.get("trace_id"):
            return None
        return TraceContext(
            trace_id=str(d["trace_id"]),
            span_id=(
                str(d["span_id"]) if d.get("span_id") is not None else None
            ),
            sampled=bool(d.get("sampled", True)),
            tenant=(
                str(d["tenant"]) if d.get("tenant") is not None else None
            ),
        )


# --------------------------------------------------------------------------
# Clock alignment
# --------------------------------------------------------------------------


class ClockSync:
    """Per-host monotonic-clock offset estimation from heartbeat RTTs.

    Each heartbeat round gives one exchange: the controller stamps its
    send time ``t`` into the probe, the agent echoes it back with its
    own clock ``agent_t``, and the controller reads ``t_recv`` at ack
    arrival. The midpoint method assumes the remote stamp was taken
    halfway through the round trip::

        offset = agent_t - (t_send + t_recv) / 2
        host_clock = controller_clock + offset

    The uncertainty is ``rtt / 2`` — the remote stamp could have been
    taken anywhere inside the round trip, so the TRUE offset lies in
    ``offset ± rtt/2`` under any path asymmetry; no tighter bound is
    honest without a symmetric-delay assumption. A sliding window keeps
    the last ``window`` exchanges per host and :meth:`offset` trusts
    the MINIMUM-RTT one (least queueing noise), so one slow ack never
    poisons the estimate.
    """

    def __init__(self, *, window: int = 32) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self._lock = threading.Lock()
        #: host -> deque[(rtt_s, offset_s)], newest last. guarded_by _lock
        self._samples: dict[str, deque] = {}

    def observe(
        self, host: str, t_send: float, t_recv: float, remote_t: float
    ) -> None:
        """Fold one heartbeat exchange in. Exchanges with a negative
        RTT (clock retrograde — cannot happen on one monotonic clock,
        CAN happen if a caller mixes clocks) are discarded."""
        rtt = t_recv - t_send
        if rtt < 0.0:
            return
        offset = remote_t - (t_send + t_recv) / 2.0
        with self._lock:
            dq = self._samples.setdefault(host, deque(maxlen=self.window))
            dq.append((rtt, offset))

    def offset(self, host: str) -> tuple[float, float] | None:
        """``(offset_s, err_s)`` for ``host`` from the minimum-RTT
        exchange in the window, or None before the first exchange.
        ``err_s`` is the half-RTT uncertainty bound of THAT exchange."""
        with self._lock:
            dq = self._samples.get(host)
            if not dq:
                return None
            rtt, off = min(dq, key=lambda s: s[0])
        return off, rtt / 2.0

    def rtt_ms(self, host: str) -> float | None:
        """Most recent exchange's RTT in milliseconds (None before the
        first exchange) — the ``host_heartbeat`` event's ``rtt_ms``."""
        with self._lock:
            dq = self._samples.get(host)
            if not dq:
                return None
            return dq[-1][0] * 1e3

    def snapshot(self) -> dict[str, dict]:
        """Per-host ``{clock_offset_s, clock_err_s, samples}`` — what
        ``cluster_summary.trace_coverage`` and merge metadata report."""
        out: dict[str, dict] = {}
        with self._lock:
            hosts = {h: list(dq) for h, dq in self._samples.items()}
        for host, samples in hosts.items():
            if not samples:
                continue
            rtt, off = min(samples, key=lambda s: s[0])
            out[host] = {
                "clock_offset_s": off,
                "clock_err_s": rtt / 2.0,
                "samples": len(samples),
            }
        return out


# --------------------------------------------------------------------------
# Cross-host stitching
# --------------------------------------------------------------------------


def merge_traces(
    exports: dict[str, dict],
    *,
    offsets: dict[str, tuple[float, float]] | None = None,
    controller: str = "controller",
) -> dict:
    """Stitch per-source Chrome exports into ONE merged trace object.

    ``exports`` maps source name (``controller`` plus host ids) to each
    ``Tracer.export()`` dict; ``offsets`` maps host id to its
    ``ClockSync.offset`` pair (host clock = controller clock + offset).
    Every non-controller span's timestamps are rebased into the
    controller's clock frame via its host's offset (a host with no
    estimate keeps its own frame — recorded honestly as
    ``clock_offset_s: None``); span and parent ids are prefixed with
    the source name so per-host ``s%06d`` counters cannot collide, and
    each span gains a ``host`` arg. Each source renders as its own
    process track (``pid`` + a ``process_name`` metadata event). The
    result's ``otherData.hosts`` carries per-source offset ± error and
    coverage counters — the merged timeline ships its own error bars.
    """
    offsets = offsets or {}
    placed: list[tuple[float, dict]] = []  # (abs_controller_ts_s, event)
    hosts_meta: dict[str, dict] = {}
    names = sorted(exports, key=lambda s: (s != controller, s))
    for pid, source in enumerate(names, start=1):
        export = exports[source] or {}
        other = export.get("otherData", {})
        t0 = float(other.get("clock_t0_s", 0.0))
        off_err = offsets.get(source) if source != controller else (0.0, 0.0)
        off = off_err[0] if off_err is not None else None
        hosts_meta[source] = {
            "clock_offset_s": off,
            "clock_err_s": off_err[1] if off_err is not None else None,
            "traces_seen": other.get("traces_seen", 0),
            "traces_kept": other.get("traces_kept", 0),
            "spans_dropped": other.get("spans_dropped", 0),
            "spans": len(export.get("traceEvents", [])),
        }
        for ev in export.get("traceEvents", []):
            ev = dict(ev)
            args = dict(ev.get("args") or {})
            for key in ("span_id", "parent_id"):
                if args.get(key):
                    args[key] = f"{source}:{args[key]}"
            if source != controller:
                args.setdefault("host", source)
            ev["args"] = args
            ev["pid"] = pid
            # Host-frame absolute seconds, mapped into the controller
            # frame when an offset estimate exists.
            abs_s = float(ev.get("ts", 0.0)) / 1e6 + t0
            if off is not None:
                abs_s -= off
            placed.append((abs_s, ev))
    base = min((t for t, _ in placed), default=0.0)
    trace_events: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": source},
        }
        for pid, source in enumerate(names, start=1)
    ]
    for abs_s, ev in sorted(placed, key=lambda p: p[0]):
        ev["ts"] = round((abs_s - base) * 1e6, 3)
        trace_events.append(ev)
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "gnot_tpu.obs.dtrace",
            "hosts": hosts_meta,
        },
    }


def write_trace(path: str, merged: dict) -> str:
    """Atomic JSON write (tmp + rename) of a merged trace file."""
    if d := os.path.dirname(path):
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, path)
    return path


# --------------------------------------------------------------------------
# Flight recorder
# --------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring buffer of the trailing ``window_s`` of spans and
    events, regardless of sample rate, dumped atomically on trigger.

    Hooked into a :class:`~gnot_tpu.obs.tracing.Tracer` via its
    ``recorder=`` argument, the recorder sees EVERY closed span —
    sampled ones on their way to the export buffer AND shadow spans of
    sampled-out traces that exist nowhere else. Event records arrive
    through :class:`FlightRecorderSink`. Retention is by time window
    (entries older than ``window_s`` behind the newest are evicted on
    append) with a hard ``max_items`` cap so a hot window stays
    bounded; evictions are counted, never silent.

    :meth:`trigger` snapshots the ring under the lock and writes the
    dump OUTSIDE it (one file per trigger, ``flight_<seq>_<kind>.json``
    via tmp+rename — a reader never sees a torn dump), tagged with the
    triggering event.
    """

    def __init__(
        self,
        dir: str,
        *,
        window_s: float = 30.0,
        max_items: int = 50_000,
        clock: Callable[[], float] = time.monotonic,
        host: str | None = None,
    ) -> None:
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if max_items < 1:
            raise ValueError(f"max_items must be >= 1, got {max_items}")
        self.dir = dir
        self.window_s = window_s
        self.max_items = max_items
        self.host = host
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque()  #: guarded_by _lock
        self._evicted = 0  #: guarded_by _lock
        self._seq = 0  #: guarded_by _lock
        self.dumps: list[str] = []  # paths written, newest last

    # -- recording ---------------------------------------------------------
    def record_span(self, span) -> None:
        """One closed span (an ``obs/tracing.Span``) into the ring."""
        entry = {
            "type": "span",
            "t": span.end,
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start": span.start,
            "end": span.end,
            "tid": span.tid,
            "args": span.args,
        }
        self._append(entry, span.end)

    def record_event(self, record: dict) -> None:
        """One sink record into the ring (stamped with the recorder's
        clock — sink records carry no monotonic time of their own)."""
        t = self._clock()
        self._append({"type": "event", "t": t, "record": dict(record)}, t)

    def _append(self, entry: dict, t: float) -> None:
        cutoff = t - self.window_s
        with self._lock:
            self._ring.append(entry)
            while self._ring and (
                len(self._ring) > self.max_items
                or self._ring[0]["t"] < cutoff
            ):
                self._ring.popleft()
                self._evicted += 1

    # -- inspection / dump -------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "host": self.host,
                "window_s": self.window_s,
                "entries": list(self._ring),
                "evicted": self._evicted,
            }

    def trigger(self, kind: str, **info) -> str:
        """Dump the current ring, tagged with the triggering event.
        Returns the written path. Every trigger writes its own file —
        a second fault arriving during a postmortem must not overwrite
        the first one's evidence."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            entries = list(self._ring)
            evicted = self._evicted
        dump = {
            "trigger": {"kind": kind, "t": self._clock(), **info},
            "host": self.host,
            "window_s": self.window_s,
            "evicted": evicted,
            "entries": entries,
        }
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, f"flight_{seq:03d}_{kind}.json")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(dump, f, default=str)
        os.replace(tmp, path)
        self.dumps.append(path)
        return path

    def watch_lockguard(self) -> None:
        """Register this recorder with ``utils/lockguard.py``: a
        runtime lock-order inversion report becomes a trigger edge
        (the black box captures the seconds BEFORE a deadlock risk,
        which is exactly when it matters)."""
        from gnot_tpu.utils import lockguard

        def _on_report(record: dict) -> None:
            self.trigger(
                "lockguard_warning",
                message=str(record.get("message", "")),
            )

        lockguard.on_report = _on_report


#: Sink-record predicates that fire a flight-recorder dump. Level
#: discipline matters: ``slo_alert`` triggers on the FIRE edge only
#: (its 'clear' edge is good news), the others are intrinsically
#: edge-emitted by their producers.
TRIGGER_EVENTS: dict[str, Callable[[dict], bool]] = {
    "slo_alert": lambda r: r.get("state") == "fire",
    "breaker_open": lambda r: True,
    "host_dead": lambda r: True,
    "non_finite_loss": lambda r: True,
}


class FlightRecorderSink:
    """MetricsSink wrapper feeding (and triggering) a flight recorder.

    Every record passes through to the inner sink unchanged, is copied
    into the recorder's ring, and — when it matches
    :data:`TRIGGER_EVENTS` — fires a dump tagged with the event. The
    wrapper is transparent: a pipeline built with or without it emits
    the identical event stream.
    """

    def __init__(self, inner, recorder: FlightRecorder) -> None:
        self._inner = inner
        self.recorder = recorder

    def log(self, **fields) -> None:
        if self._inner is not None:
            self._inner.log(**fields)
        self.recorder.record_event(fields)
        kind = fields.get("event")
        pred = TRIGGER_EVENTS.get(kind) if kind is not None else None
        if pred is not None and pred(fields):
            info = {
                k: fields[k]
                for k in ("host", "reason", "state", "objective", "tenant")
                if k in fields
            }
            self.recorder.trigger(kind, **info)

    def flush(self) -> None:
        if self._inner is not None and hasattr(self._inner, "flush"):
            self._inner.flush()
