"""Observability subsystem: on-device telemetry, run manifests, health
monitors, and host-side span tracing (ROADMAP north star: every
perf/parity PR must be debuggable).

Five pieces, all off the hot path by construction:

* ``telemetry`` — model-internals scalars (grad/param/update norms,
  per-layer MoE gate load + entropy, padding waste) computed as side
  outputs INSIDE the compiled train step and buffered as device arrays;
  the host syncs once per drain window, not per step.
* ``manifest`` — a ``run.json`` provenance snapshot (config, git rev,
  library versions, device topology, mesh shape, compile-cache stats)
  written at startup next to the metrics file.
* ``health`` — recompile detection (trace-counter deltas), slow-step
  outlier gauges, and a NaN watchdog that localizes the producing op by
  re-executing the offending batch under ``utils.debug.checked``.
* ``tracing`` — request-lifecycle and per-step phase spans (host wall
  time only, head-sampled, bounded buffer) exported as Chrome
  trace-event JSON; ``tools/trace_report.py`` prints per-kind
  percentiles, the per-bucket queue-wait/device split, and the
  critical path of the slowest request or step.
* ``metrics`` — the LIVE metrics plane: a thread-safe registry of
  counters/gauges/windowed log-bucketed histograms (O(1) memory,
  lossless replica->pool merge), a publisher streaming snapshots
  (``metrics_snapshot`` events, JSONL time series, Prometheus-text
  exposition) every ``--metrics_interval_s``, and burn-rate SLO
  evaluation emitting ``slo_alert`` fire/clear edges;
  ``tools/metrics_report.py`` renders the series and cross-checks the
  final snapshot against ``serve_summary``.
"""
