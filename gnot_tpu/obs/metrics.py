"""The live metrics plane — windowed histograms, streaming snapshots,
and burn-rate SLO alerts for the serving tier.

Until now every serving SLO number (p99, shed rate, pad waste) was
computed ONCE, post-hoc, inside ``serve_summary`` at drain time, from
unbounded per-request lists. A serving tier handling sustained traffic
needs continuously-observable health — cheap, windowed, mergeable — so
a controller (the ROADMAP autoscaling item) or a human can act *during*
the run, not after it. Three pieces:

* ``MetricsRegistry`` — a thread-safe registry of named series:
  monotonic ``Counter``s, ``Gauge``s (set or callable — polled at
  snapshot time), and **log-bucketed ``LogHistogram``s** with FIXED
  bucket bounds: O(1) memory per series regardless of traffic, and
  lossless merge across threads, replicas and the pool (adding two
  histograms' bucket counts is exact — percentile estimation error
  comes only from bucket width, never from merging). This replaces the
  unbounded latency lists the server used to keep.
* ``MetricsPublisher`` — polls the registry on an injectable clock
  every ``interval_s``, appending one JSONL row per snapshot to a time
  series file, rewriting a Prometheus-text exposition file atomically
  (tmp + rename — a scraper never sees a torn file), and emitting a
  ``metrics_snapshot`` event (with the pool-level rollup) through the
  ordinary ``MetricsSink``. ``tick()`` is the synchronous core (tests
  drive it with a fake clock); ``start()`` runs it on a daemon thread.
* ``SLOEvaluator`` — config-declared objectives (p99 latency vs the
  serve SLO, shed fraction, breaker/wedge state, queue depth,
  rollout-session loss) evaluated over FAST and SLOW burn-rate windows
  of the snapshot history. An alert FIRES only when the burn exceeds
  1.0 in BOTH windows (the fast window catches onset, the slow window
  suppresses one-interval blips) and CLEARS when the fast window
  recovers — ``slo_alert`` events are fire/clear EDGES, never
  level-triggered spam.

Everything here is stdlib-only by design (like ``obs/events.py``): the
serving hot path pays one lock + one ``bisect`` per observation, and
``tools/lint.py`` can parse the module without importing jax.

Percentile estimation error bound (documented in
docs/observability.md "Live metrics"): bucket bounds are log-spaced at
``BUCKETS_PER_DECADE`` per decade (growth factor ``g = 10^(1/20)``);
a percentile estimate is the geometric midpoint of the bucket holding
the nearest-rank observation, clamped to the observed ``[min, max]``,
so the relative error is at most ``sqrt(g) - 1`` (= ``REL_ERROR``,
~5.9%) — the bound ``tests/test_metrics_plane.py`` pins under a
10k-observation storm, and the tolerance within which a live
``metrics_snapshot`` agrees with the drain-time ``serve_summary``.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import random
import threading
import time
from typing import Callable, Iterable, Sequence

from gnot_tpu.obs import events

#: Log-bucket resolution: buckets per decade of the value axis. 20 per
#: decade over [1e-2, 1e6] ms spans 10 us .. ~17 min of latency in 160
#: buckets (+ underflow/overflow) — O(1) memory per series.
BUCKETS_PER_DECADE = 20

#: Worst-case relative error of a percentile estimate (geometric
#: midpoint of a bucket whose edges are a factor g = 10^(1/20) apart):
#: sqrt(g) - 1 ~= 5.9%. The documented agreement tolerance between the
#: live snapshots and the drain-time serve_summary.
REL_ERROR = 10.0 ** (1.0 / (2 * BUCKETS_PER_DECADE)) - 1.0

#: Bounded raw-sample retention per latency series (uniform reservoir
#: sampling): the exact-values escape hatch (``latencies_ms()``) the
#: unbounded lists used to be, at fixed memory.
RESERVOIR_SIZE = 2048


def _log_bounds(
    lo: float = 1e-2, hi: float = 1e6, per_decade: int = BUCKETS_PER_DECADE
) -> tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds. Shared by every histogram
    (same bounds => lossless merge); computed once at import."""
    n = int(round(math.log10(hi / lo) * per_decade))
    return tuple(lo * 10.0 ** (i / per_decade) for i in range(n + 1))


#: THE default bucket bounds (milliseconds). Every latency series in
#: the package uses these, so any two histograms merge losslessly.
DEFAULT_BOUNDS = _log_bounds()


class LogHistogram:
    """Fixed-bound log-bucketed histogram: O(len(bounds)) memory
    forever, lossless ``merge``, and percentile estimates within
    ``REL_ERROR`` of the exact nearest-rank value.

    Thread-safe (internal lock): the serve worker records while the
    publisher thread snapshots. Values <= bounds[0] land in the
    underflow bucket 0; values > bounds[-1] in the overflow bucket
    (estimated at the observed max, which is tracked exactly).
    """

    __slots__ = ("bounds", "_counts", "_n", "_sum", "_min", "_max", "_lock")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS):
        self.bounds = tuple(bounds)
        if len(self.bounds) < 2 or any(
            b <= a for a, b in zip(self.bounds, self.bounds[1:])
        ):
            raise ValueError("histogram bounds must be increasing, len >= 2")
        # counts[i] observes bounds[i-1] < v <= bounds[i]; counts[0] is
        # the underflow bucket, counts[len(bounds)] the overflow.
        self._counts = [0] * (len(self.bounds) + 1)
        self._n = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._n += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    # -- read side ---------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def total(self) -> float:
        with self._lock:
            return self._sum

    def copy(self) -> "LogHistogram":
        """Point-in-time copy (the merge/aggregation input)."""
        out = LogHistogram(self.bounds)
        with self._lock:
            out._counts = list(self._counts)
            out._n = self._n
            out._sum = self._sum
            out._min = self._min
            out._max = self._max
        return out

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Add ``other``'s observations into this histogram — LOSSLESS
        (bucket counts add exactly; only estimation error is bucket
        width, identical before and after the merge). Bounds must be
        identical by construction (every series uses DEFAULT_BOUNDS)."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        o = other.copy()
        with self._lock:
            for i, c in enumerate(o._counts):
                self._counts[i] += c
            self._n += o._n
            self._sum += o._sum
            self._min = min(self._min, o._min)
            self._max = max(self._max, o._max)
        return self

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile ESTIMATE: the geometric midpoint of
        the bucket holding rank ``ceil(q * n)``, clamped to the
        observed [min, max] — relative error <= REL_ERROR. None when
        empty."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"q must be in (0, 1], got {q}")
        with self._lock:
            if self._n == 0:
                return None
            rank = max(1, math.ceil(q * self._n))
            acc = 0
            idx = len(self._counts) - 1
            for i, c in enumerate(self._counts):
                acc += c
                if acc >= rank:
                    idx = i
                    break
            est = self._bucket_mid(idx)
            return min(max(est, self._min), self._max)

    def _bucket_mid(self, idx: int) -> float:
        b = self.bounds
        if idx == 0:  # underflow: at most the lowest bound
            return b[0]
        if idx >= len(b):  # overflow: clamped to observed max by caller
            return self._max
        return math.sqrt(b[idx - 1] * b[idx])

    def state(self) -> dict:
        """JSON-ready snapshot: count/sum/min/max plus the SPARSE
        nonzero bucket counts (index -> count; bounds are implied by
        DEFAULT_BOUNDS — the time-series file stays compact)."""
        with self._lock:
            return {
                "count": self._n,
                "sum": round(self._sum, 6),
                "min": self._min if self._n else None,
                "max": self._max if self._n else None,
                "buckets": {
                    str(i): c for i, c in enumerate(self._counts) if c
                },
            }

    @classmethod
    def from_state(
        cls, state: dict, bounds: Sequence[float] = DEFAULT_BOUNDS
    ) -> "LogHistogram":
        """Rebuild a histogram from a ``state()`` dict (the time-series
        reader's path back to percentiles — ``tools/metrics_report.py``
        computes windowed p50/p99 from JSONL row deltas this way)."""
        out = cls(bounds)
        for i, c in (state.get("buckets") or {}).items():
            out._counts[int(i)] = int(c)
        out._n = int(state.get("count", 0))
        out._sum = float(state.get("sum", 0.0))
        out._min = state["min"] if state.get("min") is not None else math.inf
        out._max = state["max"] if state.get("max") is not None else -math.inf
        return out

    @classmethod
    def delta(cls, now: dict, then: dict | None) -> "LogHistogram":
        """The WINDOWED histogram between two cumulative ``state()``
        snapshots: bucket-wise subtraction (exact — cumulative counts
        are monotone). ``then=None`` means "since the start". min/max
        degrade to the cumulative ones (they are not windowable), so
        windowed percentile clamps stay conservative."""
        out = cls.from_state(now)
        if then is None:
            return out
        for i, c in (then.get("buckets") or {}).items():
            out._counts[int(i)] -= int(c)
        out._n -= int(then.get("count", 0))
        out._sum -= float(then.get("sum", 0.0))
        return out


class Reservoir:
    """Bounded uniform sample of a stream (Vitter's algorithm R) — the
    raw-values retention that replaces the unbounded per-request lists:
    exact for populations <= ``size``, a uniform sample beyond. The RNG
    is seeded, so runs are replayable. Thread-safe."""

    __slots__ = ("size", "_values", "_seen", "_rng", "_lock")

    def __init__(self, size: int = RESERVOIR_SIZE, seed: int = 0):
        if size < 1:
            raise ValueError(f"reservoir size must be >= 1, got {size}")
        self.size = size
        self._values: list[float] = []
        self._seen = 0
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        with self._lock:
            self._seen += 1
            if len(self._values) < self.size:
                self._values.append(float(value))
                return
            j = self._rng.randrange(self._seen)
            if j < self.size:
                self._values[j] = float(value)

    @property
    def seen(self) -> int:
        with self._lock:
            return self._seen

    def values(self) -> list[float]:
        with self._lock:
            return list(self._values)


class Counter:
    """Monotonic counter. Thread-safe."""

    __slots__ = ("_n", "_lock")

    def __init__(self) -> None:
        self._n = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError("counters are monotonic; inc() needs n >= 0")
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n


class Gauge:
    """Point-in-time value: ``set()`` stores, or ``fn`` is called at
    snapshot time (queue depth, breaker state — no push site needed)."""

    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self, fn: Callable[[], float] | None = None):
        self._value = 0.0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def read(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value


def series_key(name: str, labels: dict | None) -> str:
    """Canonical series identity: ``name{k=v,...}`` with sorted label
    keys (the Prometheus spelling, minus quoting)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Thread-safe get-or-create registry of metric series.

    Series are identified by ``(name, labels)``; the first caller
    creates the series, later callers get the SAME object — the serve
    worker, the router and the publisher all see one set of counters.
    ``snapshot()`` is the publisher's poll: a JSON-ready dict of every
    series' state (gauges are read at poll time).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[str, tuple[str, str, dict, object]] = {}

    def _get(self, kind: str, name: str, labels: dict, make):
        key = series_key(name, labels)
        with self._lock:
            ent = self._series.get(key)
            if ent is None:
                ent = (kind, name, dict(labels), make())
                self._series[key] = ent
            elif ent[0] != kind:
                raise ValueError(
                    f"series {key!r} already registered as {ent[0]}"
                )
            return ent[3]

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(
        self, name: str, fn: Callable[[], float] | None = None, **labels
    ) -> Gauge:
        return self._get("gauge", name, labels, lambda: Gauge(fn))

    def histogram(self, name: str, **labels) -> LogHistogram:
        return self._get("histogram", name, labels, LogHistogram)

    def snapshot(self) -> dict:
        """``{series_key: {"type", "name", "labels", ...state}}`` for
        every registered series, gauges polled NOW. Counters/histograms
        report cumulative state; windowing happens downstream by
        differencing rows (``LogHistogram.delta``)."""
        with self._lock:
            items = list(self._series.items())
        out: dict[str, dict] = {}
        for key, (kind, name, labels, obj) in items:
            row: dict = {"type": kind, "name": name, "labels": labels}
            if kind == "counter":
                row["value"] = obj.value
            elif kind == "gauge":
                row["value"] = obj.read()
            else:
                row.update(obj.state())
            out[key] = row
        return out

    def unregister_gauges(self, **labels) -> int:
        """Drop every GAUGE series whose labels include ``labels`` —
        the membership-change hook: a removed replica's callback gauges
        (depth/breaker/sessions/wedge) otherwise pin its server and
        engine (device weights included) alive forever. Counters and
        histograms are deliberately kept: they are plain accumulated
        data, and the pool's cumulative rollups must keep the retired
        replica's history. Returns the number of series dropped."""
        with self._lock:
            doomed = [
                key
                for key, (kind, _, lbls, _) in self._series.items()
                if kind == "gauge"
                and all(
                    str(lbls.get(k)) == str(v) for k, v in labels.items()
                )
            ]
            for key in doomed:
                del self._series[key]
        return len(doomed)

    def aggregate_histogram(self, name: str) -> LogHistogram:
        """Lossless merge of EVERY series named ``name`` across all
        label sets — the pool view (per-replica, per-bucket series sum
        to exactly the pool histogram)."""
        out = LogHistogram()
        with self._lock:
            objs = [
                obj
                for (kind, n, _, obj) in self._series.values()
                if kind == "histogram" and n == name
            ]
        for h in objs:
            out.merge(h)
        return out

    def aggregate_counter(self, name: str) -> int:
        with self._lock:
            objs = [
                obj
                for (kind, n, _, obj) in self._series.values()
                if kind == "counter" and n == name
            ]
        return sum(o.value for o in objs)

    def aggregate_gauge(self, name: str) -> float:
        with self._lock:
            objs = [
                obj
                for (kind, n, _, obj) in self._series.values()
                if kind == "gauge" and n == name
            ]
        return float(sum(o.read() for o in objs))


# -- snapshot-level helpers (shared by the evaluator and the report) --------


def snap_counter(snap: dict, name: str, label: str | None = None,
                 value: str | None = None) -> int:
    """Sum of every counter series named ``name`` in a snapshot row,
    optionally filtered to ``labels[label] == value``."""
    total = 0
    for row in snap.values():
        if row.get("type") != "counter" or row.get("name") != name:
            continue
        if label is not None and str(row["labels"].get(label)) != str(value):
            continue
        total += int(row["value"])
    return total


def snap_gauge(snap: dict, name: str) -> float:
    return float(
        sum(
            row["value"]
            for row in snap.values()
            if row.get("type") == "gauge" and row.get("name") == name
        )
    )


def snap_histogram(snap: dict, name: str, label: str | None = None,
                   value: str | None = None) -> LogHistogram:
    """Merged histogram of every series named ``name`` in one row,
    optionally filtered to ``labels[label] == value`` (the per-tenant
    latency read: replica-labeled sub-series of one tenant merge
    losslessly into that tenant's pool view)."""
    out = LogHistogram()
    for row in snap.values():
        if row.get("type") != "histogram" or row.get("name") != name:
            continue
        if label is not None and str(row["labels"].get(label)) != str(value):
            continue
        out.merge(LogHistogram.from_state(row))
    return out


def pool_block(snap: dict) -> dict:
    """The pool-level rollup a ``metrics_snapshot`` event carries: the
    cross-replica totals and merged-histogram percentiles — the same
    numbers ``serve_summary`` reports at drain, live."""
    hist = snap_histogram(snap, "serve_request_latency_ms")
    shed = snap_counter(snap, "serve_shed_total")
    requests = snap_counter(snap, "serve_requests_total")
    return {
        "requests": requests,
        "completed": snap_counter(snap, "serve_completed_total"),
        "shed": shed,
        "shed_frac": (shed / requests) if requests else 0.0,
        "p50_ms": hist.percentile(0.50),
        "p99_ms": hist.percentile(0.99),
        "depth": snap_gauge(snap, "serve_queue_depth"),
    }


# -- Prometheus-text exposition ---------------------------------------------


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_labels(labels: dict, extra: dict | None = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    inner = ",".join(
        f'{_prom_name(str(k))}="{merged[k]}"' for k in sorted(merged)
    )
    return f"{{{inner}}}"


def exposition_text(snap: dict) -> str:
    """Render one registry snapshot in the Prometheus text exposition
    format (counters/gauges as samples, histograms as cumulative
    ``_bucket{le=...}`` + ``_sum`` + ``_count`` families)."""
    by_name: dict[str, list[tuple[dict, dict]]] = {}
    types: dict[str, str] = {}
    for row in snap.values():
        by_name.setdefault(row["name"], []).append((row["labels"], row))
        types[row["name"]] = row["type"]
    lines: list[str] = []
    for name in sorted(by_name):
        kind = types[name]
        pname = _prom_name(name)
        lines.append(
            f"# TYPE {pname} "
            f"{'histogram' if kind == 'histogram' else kind}"
        )
        for labels, row in by_name[name]:
            if kind in ("counter", "gauge"):
                lines.append(f"{pname}{_prom_labels(labels)} {row['value']}")
                continue
            counts = [0] * (len(DEFAULT_BOUNDS) + 1)
            for i, c in (row.get("buckets") or {}).items():
                counts[int(i)] = int(c)
            acc = 0
            for i, bound in enumerate(DEFAULT_BOUNDS):
                acc += counts[i]
                le = _prom_labels(labels, {"le": f"{bound:.6g}"})
                lines.append(f"{pname}_bucket{le} {acc}")
            acc += counts[-1]
            le = _prom_labels(labels, {"le": "+Inf"})
            lines.append(f"{pname}_bucket{le} {acc}")
            lines.append(
                f"{pname}_sum{_prom_labels(labels)} {row.get('sum', 0.0)}"
            )
            lines.append(
                f"{pname}_count{_prom_labels(labels)} {row.get('count', 0)}"
            )
    return "\n".join(lines) + "\n"


# -- SLO evaluation ---------------------------------------------------------


#: Objective kinds the evaluator understands (the config-declared
#: vocabulary; docs/observability.md "Live metrics" documents each).
SLO_KINDS = (
    "p99_latency_ms",  # windowed pool p99 vs threshold (ms)
    "shed_frac",       # windowed shed/submitted fraction vs threshold
    "breaker_open",    # replicas with an open breaker vs threshold
    "wedged",          # wedged replicas vs threshold (gauge)
    "queue_depth",     # pool in-system depth vs threshold
    "session_loss",    # lost rollout sessions per window vs threshold
)


class SLOObjective:
    """One declared objective: a ``kind`` (how to read the snapshot
    history), a ``threshold`` (burn = observed / threshold), and the
    fast/slow burn windows. ``clear_frac`` is the hysteresis: an active
    alert clears when the FAST burn drops below it.

    ``tenant`` scopes the objective to ONE tenant's series
    (``tenant_latency_ms`` / ``tenant_shed_total`` filtered by the
    tenant label) instead of the pool aggregates — the attribution the
    autoscaler needs to tell "interactive is burning budget" from
    "batch is flooding" (only the latency/shed kinds are per-tenant;
    breaker/wedge/depth are pool properties)."""

    __slots__ = (
        "name", "kind", "threshold", "fast_window_s", "slow_window_s",
        "clear_frac", "tenant",
    )

    def __init__(
        self,
        name: str,
        kind: str,
        threshold: float,
        *,
        fast_window_s: float = 5.0,
        slow_window_s: float = 30.0,
        clear_frac: float = 1.0,
        tenant: str | None = None,
    ):
        if kind not in SLO_KINDS:
            raise ValueError(f"unknown SLO kind {kind!r}; one of {SLO_KINDS}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        if not 0 < fast_window_s <= slow_window_s:
            raise ValueError(
                "need 0 < fast_window_s <= slow_window_s, got "
                f"{fast_window_s}/{slow_window_s}"
            )
        if tenant is not None and kind not in (
            "p99_latency_ms", "shed_frac"
        ):
            raise ValueError(
                f"SLO kind {kind!r} cannot be tenant-scoped (pool "
                "property); only p99_latency_ms/shed_frac can"
            )
        self.name = name
        self.kind = kind
        self.threshold = float(threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.clear_frac = float(clear_frac)
        self.tenant = tenant


def default_objectives(sc) -> list[SLOObjective]:
    """The serving tier's config-declared objectives (ServeConfig):
    p99 vs ``slo_p99_ms`` (when set), shed fraction vs
    ``slo_shed_frac``, plus the always-on health objectives — open
    breakers, wedge state (via progress-age gauges is the router's
    job; here the breaker gauge), pool queue depth vs the admission
    limit, and any rollout-session loss."""
    fast, slow = sc.slo_fast_window_s, sc.slo_slow_window_s
    w = dict(fast_window_s=fast, slow_window_s=slow)
    out = []
    if sc.slo_p99_ms > 0:
        out.append(
            SLOObjective("latency_p99", "p99_latency_ms", sc.slo_p99_ms, **w)
        )
    if sc.slo_shed_frac > 0:
        out.append(
            SLOObjective("shed_fraction", "shed_frac", sc.slo_shed_frac, **w)
        )
    out.append(SLOObjective("breaker_open", "breaker_open", 1.0, **w))
    out.append(SLOObjective("replica_wedged", "wedged", 1.0, **w))
    out.append(
        SLOObjective(
            "queue_saturation", "queue_depth",
            max(1.0, 0.9 * sc.queue_limit), **w,
        )
    )
    out.append(SLOObjective("session_loss", "session_loss", 1.0, **w))
    return out


def tenant_objectives(sc, tenants: Iterable[str]) -> list[SLOObjective]:
    """Per-tenant latency/shed objectives beside the pool ones: for
    each tenant the policy names, ``latency_p99:<tenant>`` (when
    ``slo_p99_ms`` is set) and ``shed_fraction:<tenant>`` (when
    ``slo_shed_frac`` is set), each reading ONLY that tenant's series.
    Their ``slo_alert`` edges carry the tenant — the attributed
    pressure signal the autoscaler's batch-deferral veto reads."""
    fast, slow = sc.slo_fast_window_s, sc.slo_slow_window_s
    w = dict(fast_window_s=fast, slow_window_s=slow)
    out = []
    for t in tenants:
        if sc.slo_p99_ms > 0:
            out.append(
                SLOObjective(
                    f"latency_p99:{t}", "p99_latency_ms", sc.slo_p99_ms,
                    tenant=t, **w,
                )
            )
        if sc.slo_shed_frac > 0:
            out.append(
                SLOObjective(
                    f"shed_fraction:{t}", "shed_frac", sc.slo_shed_frac,
                    tenant=t, **w,
                )
            )
    return out


class SLOEvaluator:
    """Streaming burn-rate evaluation over the snapshot history.

    ``observe(t, snap)`` appends one snapshot row and returns the edge
    records to emit (possibly empty): ``state="fire"`` when an
    objective's burn first reaches 1.0 in BOTH windows, ``state=
    "clear"`` when an active alert's fast burn recovers below
    ``clear_frac``. Steady violation and steady health both return
    nothing — the event stream carries edges only.

    The history is bounded: rows older than the longest slow window
    (plus one interval of slack) are dropped.
    """

    def __init__(self, objectives: Iterable[SLOObjective]):
        self.objectives = list(objectives)
        self._history: list[tuple[float, dict]] = []
        self._active: dict[str, bool] = {}
        self._lock = threading.Lock()

    def _window_base(self, now: float, window_s: float) -> dict | None:
        """The snapshot row at (or latest before) ``now - window_s`` —
        the cumulative baseline the window delta subtracts. None when
        the history starts inside the window ("since the start")."""
        cutoff = now - window_s
        base = None
        for t, snap in self._history:
            if t <= cutoff:
                base = snap
            else:
                break
        return base

    def _burn(
        self, obj: SLOObjective, now: float, snap: dict, window_s: float
    ) -> tuple[float, float | None]:
        """(burn, observed value) for one objective over one window."""
        base = self._window_base(now, window_s)
        kind = obj.kind
        if kind == "p99_latency_ms":
            # Tenant-scoped objectives read the tenant-labeled series;
            # pool objectives read the pool aggregate, exactly as
            # before.
            if obj.tenant is not None:
                name, flt = "tenant_latency_ms", {
                    "label": "tenant", "value": obj.tenant,
                }
            else:
                name, flt = "serve_request_latency_ms", {}
            now_h = snap_histogram(snap, name, **flt).state()
            base_h = (
                snap_histogram(base, name, **flt).state()
                if base is not None
                else None
            )
            p99 = LogHistogram.delta(now_h, base_h).percentile(0.99)
            if p99 is None:
                return 0.0, None
            return p99 / obj.threshold, p99
        if kind == "shed_frac":
            if obj.tenant is not None:
                shed_name, req_name = (
                    "tenant_shed_total", "tenant_requests_total",
                )
                flt = {"label": "tenant", "value": obj.tenant}
            else:
                shed_name, req_name = (
                    "serve_shed_total", "serve_requests_total",
                )
                flt = {}
            shed = snap_counter(snap, shed_name, **flt)
            reqs = snap_counter(snap, req_name, **flt)
            if base is not None:
                shed -= snap_counter(base, shed_name, **flt)
                reqs -= snap_counter(base, req_name, **flt)
            # Sheds resolve LATER than their submissions, so a window
            # can hold sheds with few (or zero) new requests — the
            # denominator is everything that MOVED in the window, never
            # smaller than the sheds themselves (a tail-of-storm shed
            # burst must read as a breach, not divide-by-zero calm).
            moved = max(reqs, shed)
            frac = shed / moved if moved > 0 else 0.0
            return frac / obj.threshold, frac
        if kind == "session_loss":
            lost = snap_counter(snap, "rollout_sessions_lost_total")
            if base is not None:
                lost -= snap_counter(base, "rollout_sessions_lost_total")
            return lost / obj.threshold, float(lost)
        # Gauge kinds: worst (max) value observed across the window's
        # rows — a gauge is a level, not a rate.
        gauge_name = {
            "breaker_open": "serve_breaker_open",
            "wedged": "serve_wedged",
            "queue_depth": "serve_queue_depth",
        }[kind]
        cutoff = now - window_s
        vals = [
            snap_gauge(s, gauge_name)
            for t, s in self._history
            if t >= cutoff
        ]
        vals.append(snap_gauge(snap, gauge_name))
        worst = max(vals)
        return worst / obj.threshold, worst

    def observe(self, t: float, snap: dict) -> list[dict]:
        edges: list[dict] = []
        with self._lock:
            for obj in self.objectives:
                burn_fast, value = self._burn(obj, t, snap, obj.fast_window_s)
                burn_slow, _ = self._burn(obj, t, snap, obj.slow_window_s)
                active = self._active.get(obj.name, False)
                # Fire at burn >= 1.0 (REACHING the threshold is the
                # breach): the always-on unit-threshold objectives —
                # one open breaker, one wedged replica, ONE lost
                # session — burn exactly 1.0 on the single-unit events
                # they exist to catch, and a strict > would make them
                # structurally unfireable.
                if not active and burn_fast >= 1.0 and burn_slow >= 1.0:
                    self._active[obj.name] = True
                    edges.append(
                        self._edge(obj, "fire", burn_fast, burn_slow, value)
                    )
                elif active and burn_fast < obj.clear_frac:
                    self._active[obj.name] = False
                    edges.append(
                        self._edge(obj, "clear", burn_fast, burn_slow, value)
                    )
            self._history.append((t, snap))
            horizon = max(
                (o.slow_window_s for o in self.objectives), default=0.0
            )
            cutoff = t - 2 * horizon
            while len(self._history) > 2 and self._history[1][0] <= cutoff:
                # Keep one row at/behind the horizon so slow-window
                # deltas always have a baseline.
                self._history.pop(0)
        return edges

    @staticmethod
    def _edge(obj, state, burn_fast, burn_slow, value) -> dict:
        return {
            "objective": obj.name,
            "kind": obj.kind,
            "state": state,
            "threshold": obj.threshold,
            "burn_fast": round(burn_fast, 4),
            "burn_slow": round(burn_slow, 4),
            "value": value,
            "fast_window_s": obj.fast_window_s,
            "slow_window_s": obj.slow_window_s,
            # Tenant-scoped objectives attribute their edges: the
            # autoscaler's deferral-vs-scale decision reads this.
            **({"tenant": obj.tenant} if obj.tenant is not None else {}),
        }

    def active(self) -> dict[str, bool]:
        with self._lock:
            return dict(self._active)


# -- the publisher ----------------------------------------------------------


class MetricsPublisher:
    """Polls a ``MetricsRegistry`` every ``interval_s`` and publishes
    each snapshot three ways: one appended JSONL row in the time-series
    file, an atomic rewrite of the Prometheus-text exposition file, and
    a ``metrics_snapshot`` event (with the ``pool_block`` rollup)
    through the sink. An attached ``SLOEvaluator`` turns each snapshot
    into zero or more ``slo_alert`` fire/clear edges.

    ``tick()`` is the synchronous unit of work (tests call it under a
    fake clock); ``start()``/``close()`` run it on a daemon thread at
    the configured cadence. ``close()`` always takes one FINAL tick, so
    the last snapshot reflects the drained end state ``serve_summary``
    reports — ``summary_agrees`` pins the two views together.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        interval_s: float,
        sink=None,
        series_path: str = "",
        exposition_path: str = "",
        evaluator: SLOEvaluator | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.registry = registry
        self.interval_s = float(interval_s)
        self.sink = sink
        self.series_path = series_path
        self.exposition_path = exposition_path
        self.evaluator = evaluator
        self._clock = clock
        self._seq = 0
        self._alerts = 0
        self._last: dict | None = None
        self._lock = threading.Lock()
        # Serializes WHOLE publish cycles: callers may tick() manually
        # (the smoke's guaranteed mid-storm snapshot) while the cadence
        # thread runs — concurrent cycles would interleave writes into
        # the one exposition tmp path / series handle and feed the
        # evaluator history out of time order.
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._closed = False
        self._fh = None
        if series_path:
            if d := os.path.dirname(series_path):
                os.makedirs(d, exist_ok=True)
            # Line-buffered append: each snapshot is ONE write() of one
            # terminated line, so a concurrent reader never sees a torn
            # row (the same contract MetricsSink keeps).
            self._fh = open(series_path, "a", buffering=1)

    # -- synchronous core --------------------------------------------------

    def tick(self) -> dict:
        """One publish cycle: snapshot -> series row -> exposition ->
        snapshot event -> SLO edges. Returns the published row.
        Thread-safe: cycles are serialized (manual ticks interleave
        with, never tear, the cadence thread's)."""
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self) -> dict:
        t = self._clock()
        snap = self.registry.snapshot()
        pool = pool_block(snap)
        with self._lock:
            self._seq += 1
            seq = self._seq
        row = {
            "seq": seq,
            "t": round(t, 6),
            "ts": time.time(),
            "interval_s": self.interval_s,
            "pool": pool,
            "series": snap,
        }
        if self._fh is not None and not self._fh.closed:
            self._fh.write(json.dumps(row) + "\n")
        if self.exposition_path:
            tmp = f"{self.exposition_path}.tmp"
            with open(tmp, "w") as f:
                f.write(exposition_text(snap))
            os.replace(tmp, self.exposition_path)
        if self.sink is not None:
            self.sink.log(
                event=events.METRICS_SNAPSHOT,
                seq=seq,
                interval_s=self.interval_s,
                series=len(snap),
                pool=pool,
                **(
                    {"series_path": self.series_path}
                    if self.series_path
                    else {}
                ),
            )
        if self.evaluator is not None:
            for edge in self.evaluator.observe(t, snap):
                with self._lock:
                    self._alerts += 1
                if self.sink is not None:
                    self.sink.log(event=events.SLO_ALERT, **edge)
        with self._lock:
            self._last = row
        return row

    # -- thread lifecycle --------------------------------------------------

    def start(self) -> "MetricsPublisher":
        if self._thread is not None:
            raise RuntimeError("publisher already started")
        self._thread = threading.Thread(
            target=self._run, name="gnot-metrics-publisher", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def close(self) -> dict:
        """Stop the thread (if any), take the FINAL snapshot, close the
        series file. Idempotent (a second close returns the final row
        without publishing again)."""
        with self._lock:
            if self._closed:
                return self._last
            self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, 2 * self.interval_s))
            self._thread = None
        row = self.tick()
        if self._fh is not None and not self._fh.closed:
            self._fh.close()
        return row

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def alerts(self) -> int:
        with self._lock:
            return self._alerts

    @property
    def last(self) -> dict | None:
        with self._lock:
            return self._last

    def stats(self) -> dict:
        """The run.json ``metrics`` block."""
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "snapshots": self._seq,
                "alerts": self._alerts,
                "series": len(self._last["series"]) if self._last else 0,
                "series_path": self.series_path or None,
                "exposition_path": self.exposition_path or None,
            }


def summary_agrees(
    summary: dict, snapshot_row: dict, *, rel: float = 2 * REL_ERROR
) -> list[str]:
    """Cross-check the drain-time ``serve_summary`` against the FINAL
    ``metrics_snapshot`` row: counters must match exactly (same
    increments, same sites), percentile estimates within ``rel`` (both
    views read the same histograms, so in practice they are equal; the
    tolerance covers the documented estimate error when one side is
    computed from raw values). Returns a list of mismatch descriptions
    — empty means the two views agree."""
    problems: list[str] = []
    pool = snapshot_row["pool"]

    def _check_exact(key: str, want, got) -> None:
        if want != got:
            problems.append(f"{key}: serve_summary={want} snapshot={got}")

    _check_exact("requests", summary["requests"], pool["requests"])
    _check_exact("completed", summary["completed"], pool["completed"])
    _check_exact(
        "shed", sum(summary.get("shed", {}).values()), pool["shed"]
    )
    for key, snap_key in (
        ("latency_p50_ms", "p50_ms"),
        ("latency_p99_ms", "p99_ms"),
    ):
        want, got = summary.get(key), pool.get(snap_key)
        if want is None and got is None:
            continue
        if want is None or got is None:
            problems.append(f"{key}: serve_summary={want} snapshot={got}")
            continue
        lo = min(want, got)
        if lo > 0 and abs(want - got) / lo > rel:
            problems.append(
                f"{key}: serve_summary={want} vs snapshot={got} "
                f"beyond rel {rel}"
            )
    return problems
