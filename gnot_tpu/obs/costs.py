"""XLA program cost extraction — the device-side sensor of the catalog.

A compiled XLA executable knows what it costs: ``cost_analysis()``
reports the HLO-level flop/byte/transcendental counts and
``memory_analysis()`` the argument/output/temp/generated-code buffer
sizes. The serving tier compiles (or AOT-hydrates) every program it
will ever dispatch, so those numbers are available exactly once per
dtype-keyed program signature — this module turns them into one plain
dict the program catalog (serve/catalog.py) stores and the capacity
model joins with live traffic.

Extraction is DUCK-TYPED and total: jaxlib's surface here has shifted
across releases (list-of-dicts vs dict from ``cost_analysis``, missing
methods on some backends, partial keys on others), and a serving tier
must never fail a dispatch because a cost probe came back thin. Every
field the catalog schema names is always present — a number when the
backend reported it, ``None`` when it did not — and any absence is
EXPLICIT via the ``unavailable`` field (the list of missing fields)
rather than silently zero: a zero-flop program and a program whose
backend would not say are different facts.

Stdlib-only (no jax import): the extractor sees only the compiled
object handed to it, so tests exercise degradation with plain stub
objects and the obs layer stays importable anywhere.
"""

from __future__ import annotations

#: Every cost field a catalog entry carries, in schema order. The first
#: three come from ``cost_analysis()`` (HLO op counts), the rest from
#: ``memory_analysis()`` (buffer-size breakdown of one execution).
COST_FIELDS = (
    "flops",
    "bytes_accessed",
    "transcendentals",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "generated_code_bytes",
)

# jaxlib's cost_analysis keys (spaces and all) -> catalog field names.
_COST_ANALYSIS_KEYS = {
    "flops": "flops",
    "bytes accessed": "bytes_accessed",
    "transcendentals": "transcendentals",
}

# CompiledMemoryStats attributes -> catalog field names.
_MEMORY_ATTRS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
}


def _as_number(value):
    """A plain JSON-safe number, or None for anything else (backends
    have returned numpy scalars, floats-as-strings and sentinels like
    -1 here; a negative count is a sentinel, not a cost)."""
    try:
        num = float(value)
    except (TypeError, ValueError):
        return None
    if num != num or num < 0:  # NaN or sentinel
        return None
    return int(num) if num == int(num) else num


def extract_costs(compiled) -> dict:
    """Cost dict for one compiled executable, total and JSON-safe.

    Returns every :data:`COST_FIELDS` key (number or None); when any
    field is missing the dict also carries ``unavailable`` — the sorted
    list of absent field names — so downstream consumers (and the
    committed artifact's acceptance check) can tell "measured zero"
    from "backend would not say".
    """
    out: dict = {f: None for f in COST_FIELDS}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    # Some jaxlib versions return one dict; others a per-partition
    # list of dicts (partition 0 carries the whole-program counts for
    # the single-program executables the serving tier compiles).
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        for src, dst in _COST_ANALYSIS_KEYS.items():
            if src in ca:
                out[dst] = _as_number(ca[src])
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for attr, dst in _MEMORY_ATTRS.items():
            if hasattr(ma, attr):
                out[dst] = _as_number(getattr(ma, attr))
    missing = sorted(f for f in COST_FIELDS if out[f] is None)
    if missing:
        out["unavailable"] = missing
    return out


def unavailable_costs(reason: str) -> dict:
    """The all-``None`` cost dict for a program whose executable could
    not be probed at all (capture raised, snapshot predates the costs
    field, ...). ``unavailable`` names every field and
    ``unavailable_reason`` says why — the explicit marker the artifact
    acceptance bar accepts in place of nonzero costs."""
    out: dict = {f: None for f in COST_FIELDS}
    out["unavailable"] = sorted(COST_FIELDS)
    out["unavailable_reason"] = reason
    return out
