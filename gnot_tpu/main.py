"""CLI driver: ``python -m gnot_tpu.main [flags]``.

Superset of the reference CLI (``/root/reference/main.py:12-156``): the
reference's nine hyperparameter flags keep their names and defaults, and
the hardcoded constants (data paths, batch size 4, lr 1e-3) become flags.
A ``--backend {jax,torch}`` selector keeps the PyTorch reference runnable
as the numerical oracle (BASELINE.json north star) when it is available
on disk; the jax path is this framework.
"""

from __future__ import annotations

import argparse
import dataclasses
import os

from gnot_tpu import config as config_lib
from gnot_tpu.config import Config, ModelConfig
from gnot_tpu.data import datasets


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="GNOT-TPU")
    # Reference flags (main.py:15-23), same names and defaults.
    p.add_argument("--n_attn_layers", type=int, default=4)
    p.add_argument("--n_attn_hidden_dim", type=int, default=256)
    p.add_argument("--n_mlp_num_layers", type=int, default=4)
    p.add_argument("--n_mlp_hidden_dim", type=int, default=256)
    p.add_argument("--n_input_hidden_dim", type=int, default=256)
    p.add_argument("--n_expert", type=int, default=3)
    p.add_argument("--n_head", type=int, default=8)
    p.add_argument("--epochs", type=int, default=100)
    # Previously-hardcoded values, now flags.
    p.add_argument("--train_data", type=str, default="", help="train pickle path")
    p.add_argument("--test_data", type=str, default="", help="test pickle path")
    p.add_argument(
        "--synthetic",
        type=str,
        default="ns2d",
        choices=sorted(datasets.SYNTHETIC),
        help="synthetic benchmark config when no pickle paths are given",
    )
    p.add_argument(
        "--synth_size", type=int, default=0,
        help="synthetic generator size (0 = its default): grid side for "
             "darcy2d (points = size^2), mesh points for the others"
    )
    p.add_argument("--n_train", type=int, default=64)
    p.add_argument("--n_test", type=int, default=16)
    p.add_argument(
        "--batch_size", type=int, default=4,
        help="samples per batch (per-process on multi-host runs: the "
             "global batch is batch_size x process_count)"
    )
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument(
        "--grad_accum", type=int, default=1,
        help="accumulate gradients over k micro-batches per optimizer "
             "update (effective batch = k x batch_size)"
    )
    p.add_argument("--seed", type=int, default=0)
    # Framework knobs.
    p.add_argument("--backend", type=str, default="jax", choices=["jax", "torch"])
    p.add_argument(
        "--compile_cache", type=str, default="",
        help="persistent XLA compile-cache dir; default: a per-user "
             "cache (re-runs skip the 30-90s first compiles). 'off' "
             "disables"
    )
    p.add_argument(
        "--device_id", type=int, default=-1,
        help="pin single-device runs to jax.devices()[i] (the reference's "
             "--gpu_id, main.py:15); -1 = automatic. Multi-chip runs use "
             "--distributed + the mesh flags instead"
    )
    p.add_argument(
        "--attention_mode", type=str, default="masked", choices=["masked", "parity"]
    )
    p.add_argument(
        "--gelu", type=str, default="", choices=["", "erf", "tanh"],
        help="GELU flavor: erf (torch nn.GELU, the reference op) or tanh "
             "(the standard approximation — ~2x cheaper on the TPU VPU). "
             "Default: erf in parity mode, tanh otherwise"
    )
    p.add_argument(
        "--attention_impl", type=str, default="xla", choices=["xla", "pallas"],
        help="xla is the only supported impl; the pallas kernel lost the "
             "honest A/B at every scale (2.4x at L=1k, 1.6x at L=16k) and "
             "its model dispatch was retired in round 4 — passing pallas "
             "raises with the dead-end analysis pointer"
    )
    p.add_argument(
        "--ffn_impl", type=str, default="xla", choices=["xla", "pallas"],
        help="pallas: VMEM-resident fused expert FFN (single-device / DP)"
    )
    p.add_argument("--dtype", type=str, default="float32", choices=["float32", "bfloat16"])
    p.add_argument(
        "--remat", action="store_true",
        help="rematerialize attention blocks in backward (less activation "
             "memory, ~1 extra forward of FLOPs — for long point clouds)"
    )
    p.add_argument(
        "--flat_params", action="store_true",
        help="flat [P]-vector parameter/optimizer layout: the AdamW "
             "update fuses into a few whole-buffer ops instead of ~2 "
             "per param leaf (same math; composes with the data/seq "
             "mesh axes only — see docs/performance.md)"
    )
    p.add_argument(
        "--scan_layers", action="store_true",
        help="run the block stack as one lax.scan over stacked per-layer "
             "params: XLA compiles one block regardless of depth (the "
             "compile-time lever for deep configs); same math"
    )
    p.add_argument(
        "--predict_out", type=str, default="",
        help="after the run, write test-set predictions to this pickle "
             "as [X, Y_pred, theta, (f...)] records (reference schema, "
             "so they round-trip through the same readers); uses the "
             "best checkpoint when --checkpoint_dir is set, else the "
             "final-epoch weights"
    )
    p.add_argument(
        "--export_torch", type=str, default="",
        help="after the run, save params as a reference-compatible torch "
             "state_dict .pth (best checkpoint when --checkpoint_dir is "
             "set, else the final weights)"
    )
    p.add_argument("--loss", type=str, default="rel_l2", choices=["rel_l2", "mse"])
    p.add_argument("--schedule", type=str, default="parity", choices=["parity", "per_step"],
                   help="parity: per-epoch OneCycle stepping (the reference bug); per_step: correct")
    p.add_argument("--checkpoint_dir", type=str, default="")
    p.add_argument("--resume", action="store_true")
    p.add_argument(
        "--eval_only", action="store_true",
        help="restore the best checkpoint and evaluate (no training)"
    )
    # Inference serving (gnot_tpu/serve/, docs/serving.md).
    p.add_argument(
        "--serve", action="store_true",
        help="serving mode (no training): restore the best checkpoint "
             "(when --checkpoint_dir is set; fresh weights otherwise), "
             "start the fault-tolerant InferenceServer (dynamic bucketed "
             "batching, admission control, deadlines, circuit breaker, "
             "graceful SIGTERM drain, hot reload), and drive the test "
             "set through it as a request stream; serve events + "
             "serve_summary flow to --metrics_path"
    )
    p.add_argument(
        "--serve_max_batch", type=int, default=4,
        help="serving: requests per dispatch; each bucket's queue "
             "flushes at this size (dispatches are padded to it, so one "
             "compiled program per bucket)"
    )
    p.add_argument(
        "--serve_max_wait_ms", type=float, default=10.0,
        help="serving: max ms a request waits for batchmates before a "
             "partial flush (the latency/utilization dial)"
    )
    p.add_argument(
        "--serve_queue_limit", type=int, default=64,
        help="serving: bounded-queue admission limit; beyond it "
             "submissions fast-fail (load shedding) instead of growing "
             "a backlog"
    )
    p.add_argument(
        "--serve_deadline_ms", type=float, default=0.0,
        help="serving: default per-request deadline (0 = none); expired "
             "requests are shed before dispatch"
    )
    p.add_argument(
        "--serve_breaker_threshold", type=int, default=3,
        help="serving: consecutive dispatch failures (NaN outputs / "
             "device errors) that trip the circuit breaker open"
    )
    p.add_argument(
        "--serve_breaker_cooldown_s", type=float, default=1.0,
        help="serving: seconds the tripped breaker rejects before one "
             "half-open trial dispatch decides recovery"
    )
    p.add_argument(
        "--drain_timeout_s", type=float, default=30.0,
        help="serving: graceful-drain budget — how long drain() waits "
             "for in-flight requests before force-resolving the "
             "stragglers"
    )
    p.add_argument(
        "--wedge_after_s", type=float, default=2.0,
        help="serving: seconds of worker-loop silence (with requests "
             "in-system) before the router treats a replica as wedged "
             "and drains its traffic to siblings"
    )
    p.add_argument(
        "--serve_inject_fault", type=str, default="",
        help="serving-side deterministic fault injection "
             "(docs/serving.md): comma-separated kind@N — "
             "slow_request@admission, nan_output@dispatch, "
             "reload_corrupt@reload"
    )
    p.add_argument(
        "--serve_packed", action="store_true",
        help="serving: packed dispatch mode ('pack, don't pad') — "
             "first-fit pack many small requests as chunk-aligned "
             "segments into ONE fixed-shape compiled program (PackPlan "
             "derived from the traffic) instead of one padded row "
             "each; per-segment unpad keeps every response exactly its "
             "own nodes, oversize requests fall back to the padded "
             "per-bucket path (docs/performance.md)"
    )
    p.add_argument(
        "--serve_pack_chunk", type=int, default=64,
        help="serving: packed-mode segment alignment in tokens "
             "(multiple of 8)"
    )
    from gnot_tpu.models.precision import SERVE_DTYPES

    p.add_argument(
        "--serve_dtype", type=str, default="float32",
        choices=list(SERVE_DTYPES),
        help="serving compute dtype (models/precision.py): bfloat16 "
             "runs the block stack in bf16 with f32 einsum "
             "accumulation, an f32 attention normalizer and an f32 "
             "output head; params stay f32 at rest (the engine "
             "publishes a cast copy per reload), batches assemble "
             "half-width through the native fused pad-and-cast "
             "packer, and every program/bucket/AOT-manifest key is "
             "dtype-keyed (docs/performance.md 'Low-precision "
             "serving')"
    )
    p.add_argument(
        "--serve_replicas", type=int, default=1,
        help="serving: engine replicas behind the compile-affinity "
             "router (serve/router.py) — each replica owns a disjoint "
             "device slice (GSPMD NamedSharding placement), its own "
             "queue/batcher/breaker, and reloads roll across the pool "
             "one replica at a time; 1 = the single-server tier "
             "(docs/serving.md 'Replicated serving')"
    )
    p.add_argument(
        "--route_policy", type=str, default="affinity",
        choices=["affinity", "least_loaded", "round_robin"],
        help="serving: replica placement policy — affinity (prefer the "
             "replica that already compiled the request's bucket; cold "
             "compiles never stall the pool), least_loaded, round_robin"
    )
    p.add_argument(
        "--serve_prewarm", type=str, default="",
        help="serving: deploy-time AOT prewarm manifest "
             "(tools/aot_prewarm.py) — hydrate each engine's compiled "
             "executables from the manifest's warm-replica snapshots "
             "before warmup, so startup/scale-out pays snapshot loads "
             "instead of XLA compiles (docs/serving.md 'Deploy-time "
             "prewarm'); must match the serving topology and model"
    )
    p.add_argument(
        "--serve_reload_every", type=int, default=0,
        help="serving demo traffic: hot-reload the checkpoint after "
             "every N requests (0 = never) — exercises the atomic "
             "weight swap under load"
    )
    p.add_argument(
        "--serve_rollout_steps", type=int, default=0,
        help="serving: autoregressive rollout mode (docs/serving.md "
             "'Rollout serving') — drive each test sample as ONE "
             "K-step stateful session (K chained dispatches, carry "
             "resident on the owning replica, per-step deadlines, "
             "streamed partial results, migration on replica failure); "
             "0 = one-shot serving"
    )
    p.add_argument(
        "--session_snapshot_every", type=int, default=1,
        help="serving: rollout-session snapshot cadence (steps between "
             "host-side carry snapshots — the state a migration "
             "replays from; 1 = every step)"
    )
    p.add_argument(
        "--session_dir", type=str, default="",
        help="serving: persist drained rollout sessions' final carry "
             "snapshots in this directory (serve/rollout.py::"
             "SessionStore) — a restarted server resumes a named "
             "session from its last snapshotted step (resume_rollout)"
    )
    p.add_argument(
        "--hosts", type=int, default=1,
        help="serving: federate the replica pool across N loopback "
             "hosts (serve/federation.py, docs/distributed.md) — each "
             "host wraps an even share of --serve_replicas behind a "
             "HostAgent; a ClusterRouter places requests/sessions over "
             "the versioned wire protocol, detects dead hosts by lease, "
             "and re-migrates their sessions to survivors; 1 = the "
             "single-host tier, byte-identical to before"
    )
    p.add_argument(
        "--federation_port", type=int, default=0,
        help="federation: base loopback-TCP port — host i listens on "
             "port+i and the controller connects real sockets instead "
             "of in-proc links (0 = in-proc transport; chaos hooks are "
             "in-proc-only)"
    )
    p.add_argument(
        "--heartbeat_interval_s", type=float, default=0.5,
        help="federation: cluster control-loop cadence — each tick "
             "probes every host's lease, sweeps the failure detector, "
             "and publishes the merged per-host series"
    )
    p.add_argument(
        "--suspect_after_s", type=float, default=2.0,
        help="federation failure detector: a host silent this long is "
             "SUSPECT — new placements avoid it and its pending "
             "one-shots are hedged onto siblings, but nothing is "
             "declared dead yet"
    )
    p.add_argument(
        "--dead_after_s", type=float, default=6.0,
        help="federation failure detector: a host silent this long is "
             "DEAD — its sessions re-migrate to survivors from "
             "persisted snapshots; must exceed --suspect_after_s (the "
             "suspicion dwell absorbs GC pauses and slow heartbeats)"
    )
    p.add_argument(
        "--flight_recorder_s", type=float, default=0.0,
        help="anomaly flight recorder (obs/dtrace.py, "
             "docs/observability.md 'Distributed tracing'): keep the "
             "last N seconds of ALL spans/events — sampled or not — in "
             "a bounded per-host ring, dumped atomically beside the "
             "trace/metrics path on trigger edges (slo_alert fire, "
             "breaker_open, host_dead, non_finite_loss, lockguard "
             "inversion); 0 = off"
    )
    p.add_argument(
        "--autoscale", action="store_true",
        help="serving: self-healing elastic pool (serve/autoscaler.py, "
             "docs/serving.md 'Elastic capacity') — an "
             "AutoscaleController scales the replica pool against live "
             "SLO/load pressure: prewarm-before-join scale-out, "
             "drain-then-remove scale-in (resident sessions migrate to "
             "siblings), self-healing replacement of dead/wedged "
             "replicas; guards: min/max bounds, per-direction "
             "cooldowns, hysteresis, flap suppression"
    )
    p.add_argument(
        "--autoscale_min", type=int, default=1,
        help="autoscale: pool floor (the controller never shrinks "
             "below it)"
    )
    p.add_argument(
        "--autoscale_max", type=int, default=4,
        help="autoscale: pool ceiling — also the device-slot topology "
             "(slots partition the device set max-wide, so an AOT "
             "manifest compiled for the max topology hydrates any "
             "scale-out slot)"
    )
    p.add_argument(
        "--autoscale_cooldown_s", type=float, default=2.0,
        help="autoscale: per-direction cooldown between actions; the "
             "flap suppressor additionally vetoes any scale-in within "
             "3 cooldowns of a scale-out"
    )
    p.add_argument(
        "--autoscale_interval_s", type=float, default=0.5,
        help="autoscale: controller tick cadence (seconds)"
    )
    p.add_argument(
        "--autoscale_up_load", type=float, default=8.0,
        help="autoscale: per-replica in-system load (requests + "
             "sessions) above which the controller scales out; must "
             "exceed --autoscale_down_load (hysteresis)"
    )
    p.add_argument(
        "--autoscale_down_load", type=float, default=1.0,
        help="autoscale: per-replica load below which a tick counts as "
             "calm; the hysteresis floor of the up/down load band"
    )
    p.add_argument(
        "--autoscale_down_ticks", type=int, default=3,
        help="autoscale: consecutive calm ticks required before any "
             "scale-in (sustained-calm guard)"
    )
    p.add_argument(
        "--autoscale_heal_after_s", type=float, default=5.0,
        help="autoscale: seconds a replica stays dead/wedged/breaker-"
             "stuck before the controller replaces it (self-healing)"
    )
    p.add_argument(
        "--metrics_interval_s", type=float, default=0.0,
        help="serving: live metrics plane (obs/metrics.py, docs/"
             "observability.md 'Live metrics') — publish a registry "
             "snapshot every N seconds: metrics_snapshot events, a "
             "JSONL time series (<metrics-stem>.series.jsonl), a "
             "Prometheus exposition file (<metrics-stem>.prom), and "
             "slo_alert burn-rate fire/clear edges; 0 = off"
    )
    p.add_argument(
        "--slo_p99_ms", type=float, default=0.0,
        help="serving SLO: windowed pool p99 latency objective (ms) "
             "the live metrics plane alerts on; 0 = no latency "
             "objective"
    )
    p.add_argument(
        "--slo_shed_frac", type=float, default=0.05,
        help="serving SLO: tolerated windowed shed fraction before "
             "the live metrics plane fires an slo_alert; 0 = off"
    )
    p.add_argument(
        "--slo_fast_window_s", type=float, default=5.0,
        help="serving SLO: fast burn-rate window (seconds) — both "
             "windows must burn > 1.0 to FIRE; the fast window "
             "clearing CLEARS (edge-triggered alerts)"
    )
    p.add_argument(
        "--slo_slow_window_s", type=float, default=30.0,
        help="serving SLO: slow burn-rate window (seconds) — the "
             "sustained-violation half of the two-window burn gate"
    )
    p.add_argument(
        "--tenant_weights", type=str, default="",
        help="serving multi-tenant isolation (docs/serving.md): "
             "per-tenant WFQ weights as tenant:weight pairs, e.g. "
             "'interactive:3,batch:1' — the batcher drains each "
             "bucket's per-tenant sub-queues deficit-round-robin by "
             "these shares, so a flooding tenant cannot starve "
             "siblings; empty (with the other tenant specs empty) = "
             "tenant mode off, byte-identical single-tenant behavior"
    )
    p.add_argument(
        "--tenant_quotas", type=str, default="",
        help="serving multi-tenant isolation: per-tenant admission "
             "quotas as tenant:limit pairs — a tenant at its pool-wide "
             "in-system limit fast-fails new work in O(1) with reason "
             "shed_tenant_quota (tenant_quota_shed event); unlisted "
             "tenants are never quota-limited"
    )
    p.add_argument(
        "--tenant_priorities", type=str, default="",
        help="serving multi-tenant isolation: per-tenant priority "
             "classes as tenant:class pairs (class 'interactive' or "
             "'batch'); under contention batch-class work is deferred "
             "first — brownout before blackout; unlisted tenants are "
             "interactive (except one literally named 'batch')"
    )
    p.add_argument("--checkpoint_every", type=int, default=0)
    p.add_argument(
        "--stop_after_epoch", type=int, default=0,
        help="fault injection: stop cleanly after N epochs as if "
             "preempted (schedule stays sized by --epochs; resume with "
             "--resume to continue the same regime); alias for "
             "--inject_fault stop_epoch@N"
    )
    p.add_argument(
        "--inject_fault", type=str, default="",
        help="deterministic fault injection (docs/robustness.md): "
             "comma-separated kind@N entries — nan_grad@step, "
             "bad_sample@step, sigterm@step, ckpt_io@count, "
             "corrupt_ckpt@epoch, stop_epoch@epochs"
    )
    p.add_argument(
        "--recovery", action="store_true",
        help="automatic NaN recovery: rolling last-good on-device "
             "snapshot every --snapshot_every steps; a non-finite loss "
             "rolls back, quarantines the offending batch, and "
             "continues — escalating to checkpoint restore after "
             "--max_rollbacks, then to the hard abort (off by default: "
             "recovery changes the training trajectory)"
    )
    p.add_argument("--snapshot_every", type=int, default=50)
    p.add_argument("--max_rollbacks", type=int, default=3)
    p.add_argument(
        "--no_preempt", action="store_true",
        help="disable graceful SIGTERM/SIGINT handling (stop at the "
             "next step boundary + 'latest' save + resume-ready exit; "
             "on by default)"
    )
    p.add_argument(
        "--preempt_sync_every", type=int, default=1,
        help="multi-host graceful preemption: allgather the stop flag "
             "every N dispatches so all hosts stop at the same step "
             "boundary (1 = every step; raise it when the per-dispatch "
             "collective matters)"
    )
    p.add_argument("--metrics_path", type=str, default="")
    p.add_argument(
        "--log_every", type=int, default=0,
        help="per-step JSONL metric cadence (0 = per-epoch only; needs --metrics_path)"
    )
    p.add_argument(
        "--telemetry", action="store_true",
        help="on-device telemetry + health monitors (obs/): grad/param/"
             "update norms, per-layer gate load/entropy, padding waste "
             "as side outputs of the compiled step, drained every "
             "--log_every steps without per-step host syncs; plus "
             "recompile detection, slow-step outliers and the NaN "
             "watchdog (docs/observability.md)"
    )
    p.add_argument("--profile_dir", type=str, default="")
    p.add_argument(
        "--trace_path", type=str, default="",
        help="host-side structured span tracing (obs/tracing.py): write "
             "a Chrome trace-event JSON here at exit — request-"
             "lifecycle spans (admission..resolve) when serving, "
             "per-step phase spans (data_iter/host_to_device/"
             "step_dispatch/...) when training; open in "
             "chrome://tracing or https://ui.perfetto.dev, summarize "
             "with tools/trace_report.py (docs/observability.md)"
    )
    p.add_argument(
        "--trace_sample_rate", type=float, default=1.0,
        help="head-based trace sampling rate in [0,1] (decided once "
             "per request/epoch, deterministically); lower it to bound "
             "tracing overhead under storm traffic"
    )
    p.add_argument(
        "--debug_checks", action="store_true",
        help="jax_debug_nans mode: the first NaN/inf raises with the "
             "producing op's location (debug builds; disables donation "
             "benefits on the failing re-run)"
    )
    p.add_argument(
        "--steps_per_dispatch", type=int, default=1,
        help="scan K training steps (over K different batches) into one "
             "compiled dispatch — cuts host->device dispatch to 1/K per "
             "step; numerically identical to K single steps"
    )
    p.add_argument("--no_bucket", action="store_true", help="pad to per-batch max (parity)")
    p.add_argument(
        "--packed", action="store_true",
        help="pack multiple samples per sequence row (chunk-aligned "
             "segments, exact per-sample attention) instead of padding "
             "each to the bucket length — recovers the ~30%% padding "
             "waste on ragged configs; masked mode; composes with the "
             "data/model/expert mesh axes (single-process)",
    )
    p.add_argument(
        "--pack_chunk", type=int, default=128,
        help="segment alignment granularity for --packed (tokens); also "
             "the per-chunk Gram contraction depth — 128 is the "
             "measured on-chip optimum (docs/performance.md)",
    )
    p.add_argument(
        "--distributed", action="store_true",
        help="train over the device mesh (sharded jit; spans hosts when "
             "launched one process per host)"
    )
    p.add_argument("--mesh_data", type=int, default=-1)
    p.add_argument("--mesh_seq", type=int, default=1)
    p.add_argument("--mesh_model", type=int, default=1)
    p.add_argument(
        "--mesh_expert", type=int, default=1,
        help="expert parallelism over the stacked soft-MoE experts "
             "(n_expert must be divisible by it)"
    )
    p.add_argument(
        "--mesh_pipe", type=int, default=1,
        help="pipeline parallelism over the attention-block stack "
             "(n_attn_layers must be divisible by it; composes with the "
             "data axis only)"
    )
    p.add_argument(
        "--microbatches", type=int, default=0,
        help="microbatches per pipeline round (0 = one per stage); the "
             "pipeline bubble is (pipe-1)/(microbatches+pipe-1)"
    )
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    cfg = config_lib.make_config(
        **{
            "data.train_path": args.train_data,
            "data.test_path": args.test_data,
            "data.synthetic": args.synthetic,
            "data.synth_size": args.synth_size,
            "data.n_train": args.n_train,
            "data.n_test": args.n_test,
            "data.batch_size": args.batch_size,
            "data.seed": args.seed,
            "data.bucket": not args.no_bucket and args.attention_mode != "parity",
            "data.packed": args.packed,
            "data.pack_chunk": args.pack_chunk,
            "optim.lr": args.lr,
            "optim.grad_accum": args.grad_accum,
            "optim.flat_params": args.flat_params,
            "optim.parity_schedule_bug": args.schedule == "parity",
            "train.epochs": args.epochs,
            "train.loss": args.loss,
            "train.checkpoint_dir": args.checkpoint_dir,
            "train.resume": args.resume,
            "train.checkpoint_every": args.checkpoint_every,
            "train.stop_after_epoch": args.stop_after_epoch,
            "train.inject_fault": args.inject_fault,
            "train.recovery": args.recovery,
            "train.snapshot_every": args.snapshot_every,
            "train.max_rollbacks": args.max_rollbacks,
            "train.graceful_preempt": not args.no_preempt,
            "train.preempt_sync_every": args.preempt_sync_every,
            "train.metrics_path": args.metrics_path,
            "train.log_every": args.log_every,
            "train.telemetry": args.telemetry,
            "train.profile_dir": args.profile_dir,
            "train.trace_path": args.trace_path,
            "train.trace_sample_rate": args.trace_sample_rate,
            "train.debug_checks": args.debug_checks,
            "train.steps_per_dispatch": args.steps_per_dispatch,
            "train.seed": args.seed,
            "train.distributed": args.distributed,
            "serve.max_batch": args.serve_max_batch,
            "serve.max_wait_ms": args.serve_max_wait_ms,
            "serve.queue_limit": args.serve_queue_limit,
            "serve.deadline_ms": args.serve_deadline_ms,
            "serve.breaker_threshold": args.serve_breaker_threshold,
            "serve.breaker_cooldown_s": args.serve_breaker_cooldown_s,
            "serve.drain_timeout_s": args.drain_timeout_s,
            "serve.wedge_after_s": args.wedge_after_s,
            "serve.inject_fault": args.serve_inject_fault,
            "serve.packed": args.serve_packed,
            "serve.pack_chunk": args.serve_pack_chunk,
            "serve.dtype": args.serve_dtype,
            "serve.replicas": args.serve_replicas,
            "serve.route_policy": args.route_policy,
            "serve.prewarm_manifest": args.serve_prewarm,
            "serve.rollout_steps": args.serve_rollout_steps,
            "serve.session_snapshot_every": args.session_snapshot_every,
            "serve.session_dir": args.session_dir,
            "serve.hosts": args.hosts,
            "serve.federation_port": args.federation_port,
            "serve.heartbeat_interval_s": args.heartbeat_interval_s,
            "serve.suspect_after_s": args.suspect_after_s,
            "serve.dead_after_s": args.dead_after_s,
            "serve.flight_recorder_s": args.flight_recorder_s,
            "serve.autoscale": args.autoscale,
            "serve.autoscale_min": args.autoscale_min,
            "serve.autoscale_max": args.autoscale_max,
            "serve.autoscale_cooldown_s": args.autoscale_cooldown_s,
            "serve.autoscale_interval_s": args.autoscale_interval_s,
            "serve.autoscale_up_load": args.autoscale_up_load,
            "serve.autoscale_down_load": args.autoscale_down_load,
            "serve.autoscale_down_ticks": args.autoscale_down_ticks,
            "serve.autoscale_heal_after_s": args.autoscale_heal_after_s,
            "serve.metrics_interval_s": args.metrics_interval_s,
            "serve.slo_p99_ms": args.slo_p99_ms,
            "serve.slo_shed_frac": args.slo_shed_frac,
            "serve.slo_fast_window_s": args.slo_fast_window_s,
            "serve.slo_slow_window_s": args.slo_slow_window_s,
            "serve.tenant_weights": args.tenant_weights,
            "serve.tenant_quotas": args.tenant_quotas,
            "serve.tenant_priorities": args.tenant_priorities,
            "mesh.data": args.mesh_data,
            "mesh.seq": args.mesh_seq,
            "mesh.model": args.mesh_model,
            "mesh.expert": args.mesh_expert,
            "mesh.pipe": args.mesh_pipe,
            "mesh.microbatches": args.microbatches,
        }
    )
    return cfg


def model_config(cfg: Config, args: argparse.Namespace, train_samples) -> ModelConfig:
    dims = datasets.infer_model_dims(train_samples)
    return dataclasses.replace(
        cfg.model,
        n_attn_layers=args.n_attn_layers,
        n_attn_hidden_dim=args.n_attn_hidden_dim,
        n_mlp_num_layers=args.n_mlp_num_layers,
        n_mlp_hidden_dim=args.n_mlp_hidden_dim,
        n_input_hidden_dim=args.n_input_hidden_dim,
        n_expert=args.n_expert,
        n_head=args.n_head,
        attention_mode=args.attention_mode,
        gelu=args.gelu,
        attention_impl=args.attention_impl,
        ffn_impl=args.ffn_impl,
        dtype=args.dtype,
        remat=args.remat,
        scan_layers=args.scan_layers,
        **dims,
    )


def run_torch_backend(args: argparse.Namespace) -> float:
    """Oracle path: train the reference PyTorch model on the same data
    pipeline (no DGL needed — our loader feeds it padded tensors)."""
    import numpy as np
    import torch

    from gnot_tpu.data.batch import Loader
    from gnot_tpu.interop.torch_oracle import build_reference_model

    cfg = config_from_args(args)
    train_samples, test_samples = datasets.load(cfg.data)
    mc = model_config(cfg, args, train_samples)
    # --device_id == the reference's --gpu_id (its main.py:15,27):
    # cuda:<id> when CUDA is available, else CPU.
    dev = torch.device("cpu")
    if args.device_id >= 0:
        if torch.cuda.is_available():
            dev = torch.device(f"cuda:{args.device_id}")
        else:
            print("note: CUDA unavailable; torch backend runs on CPU")
    torch.manual_seed(args.seed)  # reproducible init for recorded runs
    model = build_reference_model(mc).to(dev)
    opt = torch.optim.AdamW(model.parameters(), lr=args.lr)
    from torch.optim.lr_scheduler import OneCycleLR

    train_loader = Loader(
        train_samples, cfg.data.batch_size, shuffle=True, seed=cfg.data.seed, bucket=False
    )
    test_loader = Loader(test_samples, cfg.data.batch_size, bucket=False)
    sched = OneCycleLR(
        opt, max_lr=args.lr, steps_per_epoch=len(train_loader), epochs=args.epochs
    )

    from gnot_tpu.interop.torch_oracle import torch_rel_l2 as rel_l2

    def t(x):
        return torch.from_numpy(x).to(dev)

    def predict_batch(b):
        return model(
            t(b.coords),
            t(b.theta),
            [t(f) for f in b.funcs] if b.funcs is not None else None,
        )

    best = float("inf")
    best_sd = None
    for epoch in range(args.epochs):
        losses = []
        for b in train_loader:
            loss = rel_l2(predict_batch(b), t(b.y), t(b.node_mask))
            losses.append(loss.item())
            opt.zero_grad()
            loss.backward()
            opt.step()
        print(f"Epoch {epoch}, Loss: {np.mean(losses)}")
        sched.step()
        with torch.no_grad():
            metrics = [
                rel_l2(predict_batch(b), t(b.y), t(b.node_mask)).item()
                for b in test_loader
            ]
        res = float(np.mean(metrics))
        print(f"Epoch {epoch}, Test Metric: {res}")
        print("-----------------------------------")
        if res < best:
            best = res
            if args.export_torch or args.predict_out:
                # Keep the best weights so export/predict artifacts match
                # the reported best metric (same contract as the jax path).
                best_sd = {k: v.detach().clone() for k, v in model.state_dict().items()}
    print(f"\nBest Test Metric: {best}")
    if best_sd is not None:
        model.load_state_dict(best_sd)
    if args.export_torch:
        torch.save(model.state_dict(), args.export_torch)
        print(f"Exported torch state_dict to {args.export_torch}")
    if args.predict_out:
        with torch.no_grad():
            preds = []
            for b in test_loader:
                out = predict_batch(b).cpu().numpy()
                lengths = b.node_mask.sum(1).astype(int)
                preds.extend(out[i, :n] for i, n in enumerate(lengths))
        _write_predictions(test_samples, preds, args.predict_out)
    return best


def main(argv=None) -> float:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.log_every and not args.metrics_path:
        parser.error("--log_every needs --metrics_path (step records are JSONL-only)")
    if args.debug_checks:
        # Before ANY tracing: mid-process toggling does not reliably
        # instrument already-warm jit paths. Bundles jax_debug_nans
        # with the donation alias guard (utils/sanitizer.py).
        from gnot_tpu.utils.debug import enable_debug_guards

        enable_debug_guards()
    if args.backend == "torch":
        return run_torch_backend(args)
    if not args.debug_checks:
        # Honor an explicit GNOT_ALIAS_GUARD even without
        # --debug_checks (no-op when the variable is unset/off).
        from gnot_tpu.utils import sanitizer

        sanitizer.install()

    # Honor JAX_PLATFORMS even when a site hook already imported jax
    # (backends initialize lazily, so the live-config update works).
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if args.compile_cache != "off":
        from gnot_tpu.utils.cache import enable_compile_cache

        enable_compile_cache(args.compile_cache or None)

    if args.device_id >= 0:
        import jax

        if args.distributed:
            parser.error("--device_id pins a single device; drop --distributed")
        devices = jax.devices()
        if args.device_id >= len(devices):
            parser.error(
                f"--device_id {args.device_id} out of range: "
                f"{len(devices)} device(s) visible"
            )
        jax.config.update("jax_default_device", devices[args.device_id])

    if args.distributed:
        from gnot_tpu.parallel import multihost

        multihost.initialize()  # no-op single-process

    from gnot_tpu.train.trainer import Trainer
    from gnot_tpu.utils.metrics import MetricsSink

    cfg = config_from_args(args)
    train_samples, test_samples = datasets.load(cfg.data)
    mc = model_config(cfg, args, train_samples)
    # Multi-process runs shard test_samples below; predict/export want
    # the full set (identical on every host).
    full_test_samples = test_samples

    if args.distributed:
        import jax

        if jax.process_count() > 1:
            # Each host keeps only its shard; batches are per-host and
            # concatenate across processes (multihost.global_batch).
            # Equal shard sizes keep the SPMD step counts aligned.
            from gnot_tpu.parallel import multihost

            p = jax.process_count()
            for name, n in (("n_train", len(train_samples)), ("n_test", len(test_samples))):
                if n % p:
                    raise ValueError(
                        f"{name}={n} must be divisible by the {p} processes "
                        "(every host must run the same number of steps)"
                    )
            # Fix pad lengths from the PRE-shard dataset so every host
            # pads to identical shapes (SPMD global-batch assembly).
            from gnot_tpu.data.batch import fixed_pad_lengths

            pn, pf = fixed_pad_lengths(
                list(train_samples) + list(test_samples), bucket=cfg.data.bucket
            )
            cfg = dataclasses.replace(
                cfg,
                data=dataclasses.replace(cfg.data, pad_nodes=pn, pad_funcs=pf),
            )
            train_samples = multihost.shard_samples(train_samples)
            test_samples = multihost.shard_samples(test_samples)

    # Metrics are process-0-only: on multi-process runs every host
    # computes the same global metrics, and p writers on one JSONL path
    # would interleave duplicates (and the per-step float() sync would
    # hit every host). The ExitStack closes the sink on EVERY exit path
    # — an exception mid-run (NaN watchdog, preemption, Ctrl-C) must
    # not strand buffered records.
    import contextlib

    import jax

    with contextlib.ExitStack() as stack:
        sink = (
            stack.enter_context(MetricsSink(cfg.train.metrics_path))
            if cfg.train.metrics_path and jax.process_index() == 0
            else None
        )
        checkpointer = None
        if cfg.train.checkpoint_dir:
            from gnot_tpu.train.checkpoint import Checkpointer

            checkpointer = Checkpointer(
                cfg.train.checkpoint_dir,
                # Resolved numerics provenance: restore warns if a later run
                # auto-resolves a different gelu flavor (the masked-mode
                # default moved erf->tanh in round 4).
                extra_meta={
                    "gelu": mc.gelu,
                    "attention_mode": mc.attention_mode,
                    "dtype": mc.dtype,
                    # State LAYOUT provenance (not numerics): a flat-layout
                    # checkpoint restores only into a flat-layout trainer
                    # (orbax restores by structure), so the mismatch warning
                    # names the flag to flip instead of an opaque tree error.
                    "flat_params": args.flat_params,
                },
            )
        tracer = None
        federated = args.serve and cfg.serve.hosts > 1
        if cfg.train.trace_path and jax.process_index() == 0 and not federated:
            # Federated serving builds its own cluster + per-host
            # tracers inside _run_serve_federated and writes the MERGED
            # multi-process file at cluster drain — a top-level exit
            # flush here would overwrite it with controller-only spans.
            # Process-0-only like the sink: other hosts would pay span
            # recording for a buffer nothing ever flushes (one trace
            # file per run, written below by process 0).
            from gnot_tpu.obs.tracing import Tracer

            # annotate=True only under --profile_dir: spans then also
            # appear on the XLA timeline (utils/profiling.annotate), so
            # host phases align with device ops in the same viewer.
            tracer = Tracer(
                path=cfg.train.trace_path,
                sample_rate=cfg.train.trace_sample_rate,
                annotate=bool(cfg.train.profile_dir),
            )

            # On the ExitStack like the sink — a run that dies
            # mid-flight (NaN watchdog, Ctrl-C) must still write the
            # trace; those are exactly the runs whose phase spans
            # matter. Registered AFTER the sink's enter_context, so on
            # LIFO unwind the flush (and its trace_flush sink event)
            # lands before the sink closes.
            def _flush_trace(t=tracer):
                path = t.flush(sink=sink)
                print(
                    f"Wrote {len(t.snapshot())} spans to {path} "
                    "(open in chrome://tracing / "
                    "https://ui.perfetto.dev; summarize with "
                    "tools/trace_report.py)"
                )

            stack.callback(_flush_trace)
        # Live metrics plane (obs/metrics.py, --metrics_interval_s):
        # ONE registry for the whole run — the serving tier's series on
        # a --serve run, the telemetry drain's train_step_time_ms /
        # train_slow_steps_total on a training run. Process-0-only like
        # the sink/tracer. The publisher is built where the run shape
        # is known: _run_serve (with the SLO evaluator) for serving,
        # below (plain streaming) for training.
        metrics_registry = None
        if cfg.serve.metrics_interval_s > 0 and jax.process_index() == 0:
            from gnot_tpu.obs.metrics import MetricsRegistry

            metrics_registry = MetricsRegistry()
        trainer = Trainer(
            cfg, mc, train_samples, test_samples, metrics_sink=sink,
            checkpointer=checkpointer, tracer=tracer,
            metrics_registry=metrics_registry,
        )
        # Late-arriving manifest fields (e.g. the serve-warmup compile-
        # cache hit/miss stats — known only after warmup ran); the
        # post-serve re-write merges them in.
        manifest_extra: dict = {}

        def write_run_manifest():
            # Provenance manifest — docs/observability.md.
            import sys

            from gnot_tpu.obs import manifest as manifest_lib

            mpath = manifest_lib.manifest_path_for(cfg.train.metrics_path)
            manifest_lib.write_manifest(
                mpath,
                config=cfg,
                model_config=mc,
                mesh=trainer.mesh,
                argv=list(argv) if argv is not None else sys.argv[1:],
                extra={
                    "metrics_path": cfg.train.metrics_path,
                    "kind": (
                        "serve"
                        if args.serve
                        else "eval" if args.eval_only else "train"
                    ),
                    # Which checkpoint (if any) this run resumed from —
                    # including fallback provenance (checkpoint.py).
                    "restore": (
                        checkpointer.last_restore
                        if checkpointer is not None
                        else None
                    ),
                    **manifest_extra,
                },
            )

        manifests_on = cfg.train.metrics_path and jax.process_index() == 0
        if manifests_on:
            # BEFORE any heavy init: a run that crashes compiling or
            # restoring still leaves its provenance on disk.
            write_run_manifest()
        if args.serve:
            result = _run_serve(
                args, cfg, trainer, full_test_samples, sink, checkpointer,
                tracer=tracer, manifest_extra=manifest_extra,
                registry=metrics_registry,
            )
            if manifests_on:
                # Record which checkpoint serving actually restored AND
                # the warmup compile-cache hit/miss stats (known only
                # after warmup ran).
                write_run_manifest()
        elif args.eval_only:
            result = trainer.evaluate_from_checkpoint()
            if manifests_on and checkpointer is not None:
                # Record which 'best' checkpoint the eval actually
                # restored (including any fallback walk) — known only
                # after the restore above.
                write_run_manifest()
        else:
            trainer.initialize()  # every process (fit() would, identically)
            if manifests_on and checkpointer is not None:
                # Re-write with the restore provenance initialize() just
                # produced (atomic; same content plus the `restore`
                # field) — a resume that silently fell back from
                # 'latest' to 'best' must be visible in run.json, not
                # just the console.
                write_run_manifest()
            if metrics_registry is not None and cfg.train.metrics_path:
                # Stream the training run's registry (the telemetry
                # drain's step-time histogram + slow-step counter) at
                # the same cadence the serving plane uses — no SLO
                # evaluator (the declared objectives are serving ones).
                from gnot_tpu.obs.metrics import MetricsPublisher

                stem = os.path.splitext(cfg.train.metrics_path)[0]
                fit_pub = MetricsPublisher(
                    metrics_registry,
                    interval_s=cfg.serve.metrics_interval_s,
                    sink=sink,
                    series_path=f"{stem}.series.jsonl",
                    exposition_path=f"{stem}.prom",
                ).start()
                try:
                    result = trainer.fit()
                finally:
                    fit_pub.close()
                manifest_extra["metrics"] = fit_pub.stats()
                if manifests_on:
                    write_run_manifest()
            else:
                result = trainer.fit()

        if (args.export_torch or args.predict_out) and not args.eval_only:
            if checkpointer is not None:
                # Export/predict from the BEST checkpoint, not the final
                # epoch, so both artifacts correspond to the reported best
                # metric. (eval_only already restored it into trainer.state.)
                restored = checkpointer.restore_best(trainer.state)
                if restored is not None:
                    trainer.state = restored[0]
            else:
                print(
                    "note: no --checkpoint_dir, so export/predict artifacts "
                    "use the FINAL-epoch weights, not the reported best"
                )
        if args.export_torch:
            _export_torch(trainer, mc, args.export_torch)
        if args.predict_out:
            # Collective on multi-process runs (params allgather inside
            # predict): every process computes the full predictions, only
            # process 0 writes the file.
            preds = trainer.predict(full_test_samples)
            if jax.process_index() == 0:
                _write_predictions(full_test_samples, preds, args.predict_out)
    return result


def _run_serve(
    args, cfg, trainer, samples, sink, checkpointer, tracer=None,
    manifest_extra=None, registry=None,
) -> float:
    """``--serve``: restore weights, start the fault-tolerant serving
    tier — ONE InferenceServer, or with ``--serve_replicas N`` the
    compile-affinity ``ReplicaRouter`` over N mesh-sliced engine
    replicas — drive the test set through it as a request stream (the
    in-process demo/smoke traffic; a network transport would sit in
    front of ``submit``), drain gracefully, and report. A SIGTERM
    mid-stream stops admission and drains in-flight requests
    (resilience.preemption). Reloads roll across the replica pool one
    replica at a time. Warmup runs under the compile-cache probe and
    records cache hit/miss into the run manifest. Returns the
    completed-request fraction."""
    import jax

    from gnot_tpu.resilience.faults import FaultInjector
    from gnot_tpu.resilience.preemption import PreemptionHandler
    from gnot_tpu.serve import (
        CheckpointReloader,
        InferenceServer,
        ReplicaRouter,
        build_replicas,
    )
    from gnot_tpu.utils.cache import compile_cache_probe

    if jax.process_count() > 1:
        raise ValueError(
            "--serve is single-process (the request-serving layer does "
            "not compose with multi-host SPMD; single-process meshes "
            "are fine)"
        )
    trainer.initialize()
    if checkpointer is not None:
        restored = checkpointer.restore_best(
            trainer.state
        ) or checkpointer.restore_latest(trainer.state)
        if restored is not None:
            trainer.state = restored[0]
        else:
            print("note: no restorable checkpoint — serving fresh weights")
    sc = cfg.serve
    replicated = sc.replicas > 1 or sc.autoscale
    if replicated and trainer.mesh is not None:
        raise ValueError(
            "--serve_replicas/--autoscale build their own per-replica "
            "mesh slices; drop --distributed (the trainer mesh) when "
            "serving replicated"
        )
    if replicated and (
        trainer.model.config.scan_layers or cfg.optim.flat_params
    ):
        # build_replicas' forward is the standard-layout apply_batch;
        # the stacked (scan_layers) and flat [P]-vector param layouts
        # need the trainer's layout-aware forward, which replicated
        # serving does not thread yet. Fail with the flag to flip
        # instead of a flax structure error at warmup.
        raise ValueError(
            "--serve_replicas serves the standard param layout only; "
            "drop --scan_layers/--flat_params for replicated serving "
            "(single-server --serve supports them)"
        )
    if sc.hosts > 1:
        # Topology-honest federation (serve/federation.py,
        # docs/distributed.md): the pool splits evenly across loopback
        # hosts and a ClusterRouter drives the same storm through the
        # wire protocol. A separate function — the single-host path
        # below must stay byte-identical when --hosts is 1.
        return _run_serve_federated(
            args, cfg, trainer, samples, sink, manifest_extra
        )
    # Packed dispatch ("pack, don't pad", docs/performance.md): derive
    # the ONE fixed dispatch shape from the traffic itself — the same
    # samples we are about to serve are the representative set.
    pack_plan = None
    if sc.packed:
        import jax as _jax

        from gnot_tpu.data.batch import PackPlan

        pack_plan = PackPlan.for_slices(
            samples,
            chunk=sc.pack_chunk,
            batch_size=sc.max_batch,
            per_devices=(
                len(_jax.devices())
                // (sc.autoscale_max if sc.autoscale else sc.replicas)
                if replicated
                else 1
            ),
        )
    reload_fn = (
        CheckpointReloader(checkpointer, trainer.state)
        if checkpointer is not None
        else None
    )
    replicas = None
    autoscale_slots = None
    if sc.autoscale:
        # Elastic pool: device slots partition the device set
        # autoscale_max-wide (NOT founding-pool-wide), so every future
        # scale-out replica has a slice waiting — and an AOT manifest
        # compiled for the max topology hydrates any slot.
        from gnot_tpu.serve import build_replica

        devices = list(jax.devices())
        if sc.autoscale_max > len(devices):
            raise ValueError(
                f"--autoscale_max {sc.autoscale_max} needs at least one "
                f"device per replica; only {len(devices)} visible (CPU: "
                "raise --xla_force_host_platform_device_count)"
            )
        per = len(devices) // sc.autoscale_max
        autoscale_slots = [
            devices[i * per : (i + 1) * per]
            for i in range(sc.autoscale_max)
        ]
        tl = trainer.train_loader

        # ONE construction path for founding and scale-out replicas (a
        # kwarg added here reaches both, or they silently diverge); the
        # AutoscaleController gets this same factory.
        def autoscale_factory(rid, slot):
            return build_replica(
                trainer.model,
                trainer.state.params,
                rid,
                autoscale_slots[slot],
                batch_size=sc.max_batch,
                bucket=cfg.data.bucket,
                pad_nodes=tl.pad_nodes,
                pad_funcs=tl.pad_funcs,
                dtype=sc.dtype,
            )

        replicas = [autoscale_factory(i, i) for i in range(sc.replicas)]
    elif sc.replicas > 1:
        tl = trainer.train_loader
        replicas = build_replicas(
            trainer.model,
            trainer.state.params,
            sc.replicas,
            batch_size=sc.max_batch,
            bucket=cfg.data.bucket,
            pad_nodes=tl.pad_nodes,
            pad_funcs=tl.pad_funcs,
            dtype=sc.dtype,
        )
    else:
        engine = trainer.inference_engine(dtype=sc.dtype)
    # One-time native-packer attribution (satellite of the dispatch
    # hot-path work): whether batch assembly/unpad run the C++ packer
    # or the Python fallback, as an event AND a run.json field — a
    # bench artifact from this run names the path that produced it.
    from gnot_tpu import native
    from gnot_tpu.obs import events as events_lib

    packer = native.status()
    if sink is not None:
        sink.log(
            event=events_lib.NATIVE_PACKER,
            available=packer["available"],
            impl=packer["impl"],
            pack_native_min_bytes=packer["pack_native_min_bytes"],
            unpad_native_min_bytes=packer["unpad_native_min_bytes"],
            **({"so": packer["so"]} if packer["so"] else {}),
            **({"error": packer["error"]} if packer["error"] else {}),
        )
    if manifest_extra is not None:
        manifest_extra["native_packer"] = packer
        manifest_extra["serve_dtype"] = sc.dtype
    prewarm = None
    if sc.prewarm_manifest:
        # Deploy-time AOT prewarm (serve/aot.py): validate the
        # manifest against this topology up front — snapshots are
        # device-assignment-bound, so a manifest compiled for a
        # different replica count cannot hydrate this pool; and
        # dtype-bound, so a manifest compiled at another serving
        # dtype is the wrong program family, not a warm one.
        from gnot_tpu.serve import aot

        prewarm = aot.load_manifest(sc.prewarm_manifest)
        if sc.autoscale:
            # An elastic pool hydrates from the MAX-topology manifest:
            # founding replicas take their slots' blocks now, and every
            # scale-out slot has a block waiting (prewarm-before-join).
            expect = sc.autoscale_max
        else:
            expect = sc.replicas if sc.replicas > 1 else 1
        if prewarm["replicas"] != expect:
            raise ValueError(
                f"--serve_prewarm manifest was compiled for "
                f"{prewarm['replicas']} replicas; this run serves "
                f"{expect} — re-run tools/aot_prewarm.py for the "
                "target topology"
            )
        if prewarm.get("dtype", "float32") != sc.dtype:
            raise ValueError(
                f"--serve_prewarm manifest was compiled at serve "
                f"dtype {prewarm.get('dtype', 'float32')!r}; this run "
                f"serves {sc.dtype!r} — re-run tools/aot_prewarm.py "
                "with the matching --serve_dtype"
            )
    # Multi-tenant isolation plane (serve/policies.py, docs/serving.md
    # "Multi-tenant isolation"): ONE TenantPolicy shared by every
    # replica server — per-tenant WFQ weights at the batcher, pool-wide
    # admission quotas, priority classes — or None (all three specs
    # empty): tenant mode off, the byte-identical single-tenant path.
    from gnot_tpu.serve import TenantPolicy

    tenants = TenantPolicy.from_specs(
        weights=sc.tenant_weights,
        quotas=sc.tenant_quotas,
        priorities=sc.tenant_priorities,
    )
    # Live metrics plane (obs/metrics.py): one registry shared by the
    # whole serving tier (per-replica servers record replica-labeled
    # series that merge losslessly into the pool view), a publisher
    # polling it every --metrics_interval_s, and the config-declared
    # SLO objectives evaluated over fast/slow burn-rate windows.
    publisher = None
    if sc.metrics_interval_s > 0:
        import tempfile

        from gnot_tpu.obs import metrics as metrics_lib

        # main() hands over the run's registry (the trainer's telemetry
        # drain already records into it); a direct library caller gets
        # a fresh one.
        if registry is None:
            registry = metrics_lib.MetricsRegistry()
        if cfg.train.metrics_path:
            stem = os.path.splitext(cfg.train.metrics_path)[0]
        else:
            stem = os.path.join(
                tempfile.mkdtemp(prefix="gnot_metrics_"), "serve"
            )
        publisher = metrics_lib.MetricsPublisher(
            registry,
            interval_s=sc.metrics_interval_s,
            sink=sink,
            series_path=f"{stem}.series.jsonl",
            exposition_path=f"{stem}.prom",
            evaluator=metrics_lib.SLOEvaluator(
                metrics_lib.default_objectives(sc)
                # Per-tenant latency/shed objectives beside the pool
                # ones: their slo_alert edges carry the tenant, the
                # autoscaler's attribution signal.
                + (
                    metrics_lib.tenant_objectives(sc, tenants.tenants)
                    if tenants is not None
                    else []
                )
            ),
        )
    session_store = None
    if sc.session_dir:
        from gnot_tpu.serve import SessionStore

        session_store = SessionStore(sc.session_dir)
    with PreemptionHandler() as preempt:
        common = dict(
            max_batch=sc.max_batch,
            max_wait_ms=sc.max_wait_ms,
            queue_limit=sc.queue_limit,
            default_deadline_ms=sc.deadline_ms,
            breaker_threshold=sc.breaker_threshold,
            breaker_cooldown_s=sc.breaker_cooldown_s,
            pack_plan=pack_plan,
            sink=sink,
            reload_fn=reload_fn,
            faults=FaultInjector.from_spec(sc.inject_fault),
            preempt=preempt,
            tracer=tracer,
            session_snapshot_every=sc.session_snapshot_every,
            metrics=registry,
            session_store=session_store,
            tenants=tenants,
        )
        if replicas is not None:
            server = ReplicaRouter(
                replicas,
                route_policy=sc.route_policy,
                wedge_after_s=sc.wedge_after_s,
                **common,
            )
        else:
            server = InferenceServer(engine, **common)
        # Serving-startup discipline (docs/serving.md): precompile one
        # program per bucket the traffic will hit — a cold XLA compile
        # landing under a tight deadline would shed everything behind
        # it. Packed mode still warms the padded buckets too (the
        # oversize fallback path). With a prewarm manifest the
        # executables hydrate from warm-replica snapshots FIRST (no
        # traces, no compiles; replica_warm events flow to the sink),
        # and warmup only compiles whatever the manifest missed. The
        # probe records persistent-compile-cache hits/misses for the
        # run manifest: warm time is THE replica scale-out cost, and
        # whether it compiled fresh, loaded cached executables, or
        # skipped compiling entirely is the number to watch (ROADMAP
        # cold-start item).
        prewarm_stats = None
        with compile_cache_probe() as warm_stats:
            if prewarm is not None:
                if replicas is not None:
                    prewarm_stats = server.prewarm_from(prewarm)
                    mismatched = [
                        rid
                        for rid, st in prewarm_stats.items()
                        if st.get("reason") == "params_mismatch"
                    ]
                    if mismatched:
                        print(
                            "note: --serve_prewarm manifest was built "
                            "for a different model/param layout; "
                            f"replicas {mismatched} fall back to cold "
                            "warmup"
                        )
                else:
                    from gnot_tpu.serve import aot

                    prewarm_stats = aot.hydrate_block(engine, prewarm, 0)
                    if prewarm_stats.get("reason") == "params_mismatch":
                        print(
                            "note: --serve_prewarm manifest was built "
                            "for a different model/param layout; "
                            "falling back to cold warmup"
                        )
            if replicas is not None:
                warmed = sum(
                    r.warm(samples, rows=sc.max_batch, pack_plan=pack_plan)
                    for r in replicas
                )
            else:
                warmed = engine.warmup(samples, rows=sc.max_batch)
                if pack_plan is not None:
                    warmed += engine.warmup_packed(samples, pack_plan)
        if manifest_extra is not None:
            manifest_extra["warmup_cache"] = {
                "programs_warmed": warmed,
                "replicas": sc.replicas,
                **(
                    {"prewarm": prewarm_stats}
                    if prewarm_stats is not None
                    else {}
                ),
                **warm_stats,
            }
        server.start()
        if publisher is not None:
            publisher.start()
        # Self-healing elastic pool (serve/autoscaler.py): the
        # controller subscribes to the registry/evaluator the publisher
        # polls and scales the founding pool between the configured
        # bounds while the storm runs.
        controller = None
        if sc.autoscale:
            from gnot_tpu.serve import AutoscaleController

            controller = AutoscaleController(
                server,
                replica_factory=autoscale_factory,
                min_replicas=sc.autoscale_min,
                max_replicas=sc.autoscale_max,
                interval_s=sc.autoscale_interval_s,
                cooldown_s=sc.autoscale_cooldown_s,
                up_load=sc.autoscale_up_load,
                down_load=sc.autoscale_down_load,
                down_ticks=sc.autoscale_down_ticks,
                heal_after_s=sc.autoscale_heal_after_s,
                drain_timeout_s=sc.drain_timeout_s,
                registry=registry,
                evaluator=(
                    publisher.evaluator if publisher is not None else None
                ),
                warm_samples=samples,
                pack_plan=pack_plan,
                prewarm_manifest=prewarm,
                sink=sink,
                tenants=tenants,
            ).start()
        rollout_k = sc.rollout_steps
        try:
            summary, futures = _serve_storm(
                args, sc, server, samples, checkpointer, preempt,
                controller=controller,
            )
        finally:
            # The controller and publisher threads must stop BEFORE the
            # sink can close (the enclosing ExitStack) on any exit path
            # — a wedged storm or mid-stream crash must not leave a
            # daemon thread ticking into a closed file. close() is
            # idempotent: the success path below re-calls it for the
            # final row without publishing twice.
            if controller is not None:
                controller.close()
            if publisher is not None:
                publisher.close()
        if controller is not None:
            # Already closed (storm success path closes it before the
            # drain; the finally covers error paths) — just read.
            ast_stats = controller.stats()
            if manifest_extra is not None:
                manifest_extra["autoscale"] = {
                    **ast_stats,
                    "replica_seconds": round(
                        controller.replica_seconds(), 3
                    ),
                }
            print(
                f"Autoscale: pool [{sc.autoscale_min}, "
                f"{sc.autoscale_max}], {ast_stats['scale_ups']} up / "
                f"{ast_stats['scale_downs']} down / "
                f"{ast_stats['replaces']} replaced over "
                f"{ast_stats['ticks']} ticks; "
                f"{controller.replica_seconds():.1f} replica-seconds"
            )
        if publisher is not None:
            # The FINAL snapshot was taken AFTER the drain, so it reads
            # the settled end-state counters — the drain-time
            # serve_summary and the live plane's last word must agree
            # (within the documented histogram estimate bound).
            from gnot_tpu.obs import metrics as metrics_lib

            final = publisher.close()
            disagreements = metrics_lib.summary_agrees(summary, final)
            if disagreements:
                print(
                    "WARNING: serve_summary and the final "
                    f"metrics_snapshot disagree: {disagreements}"
                )
            if manifest_extra is not None:
                manifest_extra["metrics"] = {
                    **publisher.stats(),
                    "summary_agrees": not disagreements,
                }
            print(
                f"Metrics plane: {publisher.seq} snapshots every "
                f"{sc.metrics_interval_s}s, {publisher.alerts} SLO "
                f"alert edges -> {publisher.series_path} + "
                f"{publisher.exposition_path} (summarize with "
                "tools/metrics_report.py)"
            )
    routing = summary.get("routing")
    sessions = summary.get("sessions")
    print(
        f"Serve: {summary['completed']}/{summary['requests']} ok, "
        f"shed={summary['shed']}, breaker_trips={summary['breaker_trips']}, "
        f"reloads={summary['reloads']}, "
        f"p50={summary['latency_p50_ms']}ms p99={summary['latency_p99_ms']}ms, "
        f"compiled_shapes={summary['compiled_shapes']}"
        + (
            f", replicas={routing['replicas']} policy={routing['policy']} "
            f"spills={routing['spills']}"
            if routing
            else ""
        )
        + (
            f", sessions={sessions['completed']}/{sessions['started']} "
            f"complete (migrated={sessions.get('migrated', 0)}, "
            f"lost={sessions.get('lost', sessions.get('failed', 0))}), "
            f"step_p50={sessions['step_latency_p50_ms']}ms"
            if sessions
            else ""
        )
    )
    if rollout_k:
        done = sum(1 for f in futures if f.result().ok)
        return done / max(1, len(futures))
    return summary["completed"] / max(1, summary["requests"])


def _run_serve_federated(
    args, cfg, trainer, samples, sink, manifest_extra=None
) -> float:
    """``--serve --hosts N``: the federated serving tier
    (serve/federation.py, docs/distributed.md). The replica pool splits
    evenly across N loopback hosts — each behind a ``HostAgent``
    speaking the versioned wire protocol — and a ``ClusterRouter``
    drives the same demo storm through lease-checked, partition-tolerant
    placement; a background control loop ticks the failure detector at
    ``--heartbeat_interval_s``. ``--federation_port`` swaps the in-proc
    links for real loopback TCP. Returns the completed fraction."""
    import threading

    from gnot_tpu.resilience.faults import FaultInjector
    from gnot_tpu.resilience.preemption import PreemptionHandler
    from gnot_tpu.serve import build_replicas
    from gnot_tpu.serve.federation import (
        build_local_federation,
        topology_key,
    )

    sc = cfg.serve
    per = sc.replicas // sc.hosts  # divisibility config-validated
    tl = trainer.train_loader
    replicas = build_replicas(
        trainer.model,
        trainer.state.params,
        sc.replicas,
        batch_size=sc.max_batch,
        bucket=cfg.data.bucket,
        pad_nodes=tl.pad_nodes,
        pad_funcs=tl.pad_funcs,
        dtype=sc.dtype,
    )
    groups = [replicas[i * per : (i + 1) * per] for i in range(sc.hosts)]
    session_store = None
    if sc.session_dir:
        from gnot_tpu.serve import SessionStore

        # The migration substrate: a survivor resumes a dead host's
        # sessions from snapshots persisted here. Without it, a host
        # death falls back to restart-from-zero re-placement.
        session_store = SessionStore(sc.session_dir)
    manifests = None
    if sc.prewarm_manifest:
        from gnot_tpu.serve import aot

        manifest = aot.load_manifest(sc.prewarm_manifest)
        if manifest["replicas"] != per:
            raise ValueError(
                f"--serve_prewarm manifest was compiled for "
                f"{manifest['replicas']} replicas; each federated host "
                f"pools {per} — re-run tools/aot_prewarm.py for the "
                "per-host topology"
            )
        if manifest.get("dtype", "float32") != sc.dtype:
            raise ValueError(
                f"--serve_prewarm manifest was compiled at serve dtype "
                f"{manifest.get('dtype', 'float32')!r}; this run serves "
                f"{sc.dtype!r}"
            )
        manifests = {topology_key(sc.hosts, per): manifest}
    series_path = None
    if cfg.train.metrics_path:
        stem = os.path.splitext(cfg.train.metrics_path)[0]
        series_path = f"{stem}.series.jsonl"
    metrics_factory = None
    if sc.metrics_interval_s > 0 or series_path:
        from gnot_tpu.obs import metrics as metrics_lib

        metrics_factory = metrics_lib.MetricsRegistry
    fi = FaultInjector.from_spec(sc.inject_fault)
    host_ids = [f"host{i}" for i in range(sc.hosts)]
    # ONE injector shared by every hook level (link, agent, local
    # router): the single-fire gate inside the injector keeps an
    # armed `host_kill@3` from killing all N hosts at once.
    chaos = {h: fi for h in host_ids} if fi is not None else None
    # Cluster-scoped distributed tracing + flight recorder
    # (obs/dtrace.py, docs/observability.md "Distributed tracing"):
    # the sampling decision lives in the CLUSTER tracer; per-host
    # tracers only adopt it from the wire trace_ctx. --trace_path gets
    # the MERGED multi-process file (controller + every host's spans
    # rebased by the heartbeat clock offsets), written at drain.
    cluster_tracer = None
    tracer_factory = None
    recorders = None
    if sc.flight_recorder_s > 0:
        from gnot_tpu.obs import dtrace

        flight_dir = (
            os.path.dirname(cfg.train.trace_path)
            or os.path.dirname(cfg.train.metrics_path)
            or "."
        )
        recorders = {
            h: dtrace.FlightRecorder(
                flight_dir, window_s=sc.flight_recorder_s, host=h
            )
            for h in ["controller", *host_ids]
        }
        # The controller's ring is the cluster black box: host_dead
        # fires HERE (a dead host cannot dump its own box), and the
        # lockguard hook is process-global so one registrant suffices.
        recorders["controller"].watch_lockguard()
    if cfg.train.trace_path or recorders is not None:
        from gnot_tpu.obs.tracing import Tracer

        # Without --trace_path nothing exports — rate 0 keeps the
        # export buffers empty, and the rings still fill with
        # "!"-prefixed shadow spans (recorder-only black box).
        rate = cfg.train.trace_sample_rate if cfg.train.trace_path else 0.0

        def _tracer_for(host_id):
            return Tracer(
                sample_rate=rate,
                recorder=(recorders or {}).get(host_id),
            )

        cluster_tracer = _tracer_for("controller")
        tracer_factory = _tracer_for
    cluster, agents = build_local_federation(
        groups,
        sink=sink,
        suspect_after_s=sc.suspect_after_s,
        dead_after_s=sc.dead_after_s,
        session_store=session_store,
        link_faults=None if sc.federation_port else chaos,
        host_faults=chaos,
        manifests=manifests,
        series_path=series_path,
        metrics_factory=metrics_factory,
        tcp_base_port=sc.federation_port,
        tracer_factory=tracer_factory,
        cluster_tracer=cluster_tracer,
        trace_path=cfg.train.trace_path or None,
        recorders=recorders,
        router_kwargs=dict(
            max_batch=sc.max_batch,
            max_wait_ms=sc.max_wait_ms,
            queue_limit=sc.queue_limit,
            default_deadline_ms=sc.deadline_ms,
            breaker_threshold=sc.breaker_threshold,
            breaker_cooldown_s=sc.breaker_cooldown_s,
            session_snapshot_every=sc.session_snapshot_every,
            route_policy=sc.route_policy,
            faults=fi,
        ),
    )
    rollout_k = sc.rollout_steps
    futures = []
    with PreemptionHandler() as preempt:
        for a in agents.values():
            a.router.start()
        # Same startup discipline as single-host: every bucket compiles
        # on every replica BEFORE traffic, or cold compiles land under
        # deadlines mid-storm.
        warmed = sum(r.warm(samples, rows=sc.max_batch) for r in replicas)
        if manifest_extra is not None:
            manifest_extra["warmup_cache"] = {
                "programs_warmed": warmed,
                "replicas": sc.replicas,
                "hosts": sc.hosts,
            }
        stop = threading.Event()

        def _control_loop():
            while not stop.is_set():
                cluster.tick()
                stop.wait(sc.heartbeat_interval_s)

        ticker = threading.Thread(
            target=_control_loop, name="fed-control", daemon=True
        )
        ticker.start()
        try:
            for s in samples:
                if preempt.triggered:
                    break
                if rollout_k:
                    futures.append(cluster.submit_rollout(s, rollout_k))
                else:
                    futures.append(cluster.submit(s))
            session_timeout = sc.drain_timeout_s * max(1, rollout_k)
            for f in futures:
                f.result(timeout=session_timeout)
        finally:
            stop.set()
            ticker.join(timeout=5)
            summary = cluster.drain(sc.drain_timeout_s)
            for a in agents.values():
                a.stop()
    print(
        f"Federated serve: {sc.hosts} hosts x {per} replicas "
        f"({'tcp' if sc.federation_port else 'in-proc'}), "
        f"{summary['completed']}/{summary['requests']} ok, "
        f"shed={summary['shed']}, sessions={summary['sessions']} "
        f"(remigrated={summary['remigrated']}, lost={summary['lost']}), "
        f"hosts_dead={summary['hosts_dead']}, "
        f"protocol_errors={summary['protocol_errors']}"
    )
    if cfg.train.trace_path and cluster.merged_trace is not None:
        print(
            f"Wrote merged cluster trace "
            f"({len(cluster.merged_trace['traceEvents'])} spans, "
            f"{len(cluster.merged_trace['otherData']['hosts'])} sources) "
            f"to {cfg.train.trace_path} (open in https://ui.perfetto.dev; "
            "summarize with tools/trace_report.py)"
        )
    if recorders is not None:
        dumps = [p for r in recorders.values() for p in r.dumps]
        if dumps:
            print(
                f"Flight recorder dumped {len(dumps)} ring(s): "
                + ", ".join(dumps)
            )
    if manifest_extra is not None:
        manifest_extra["federation"] = {
            k: v for k, v in summary.items() if k != "per_host"
        }
    if rollout_k:
        done = sum(1 for f in futures if f.result().ok)
        return done / max(1, len(futures))
    return summary["completed"] / max(1, summary["requests"])


def _serve_storm(
    args, sc, server, samples, checkpointer, preempt, controller=None
):
    """Drive the in-process demo storm through a started server and
    drain it: returns ``(summary, futures)``. Factored out of
    ``_run_serve`` so the metrics publisher can wrap the WHOLE storm in
    one try/finally — any exit path stops the publisher thread before
    the sink closes. The autoscale ``controller`` (when elastic) is
    closed BETWEEN the last resolved future and the pool drain, so a
    scale action can never race the final rollup."""
    futures = []
    rollout_k = sc.rollout_steps
    for i, s in enumerate(samples):
        if preempt.triggered:
            break
        if rollout_k:
            # Rollout serving (docs/serving.md "Rollout serving"):
            # each sample becomes one K-step stateful session — K
            # chained dispatches, carry resident on the owning
            # replica, streamed partial results, migration on
            # owner failure.
            futures.append(server.submit_rollout(s, rollout_k))
        else:
            futures.append(server.submit(s))
        if (
            args.serve_reload_every
            and checkpointer is not None
            and (i + 1) % args.serve_reload_every == 0
        ):
            # On the router this is the ROLLING reload: one replica
            # warms at a time, old weights keep serving.
            server.reload(deadline_ms=sc.deadline_ms)
    session_timeout = sc.drain_timeout_s * max(1, rollout_k)
    for f in futures:
        f.result(timeout=session_timeout)
    if controller is not None:
        controller.close()
    return server.drain(sc.drain_timeout_s), futures


def _write_predictions(samples, preds, path: str) -> None:
    """Write predictions as reference-schema records ([X, Y_pred, theta,
    (f...)]) so they round-trip through the same readers."""
    datasets.save_pickle(
        [dataclasses.replace(s, y=p) for s, p in zip(samples, preds)], path
    )
    print(f"Wrote {len(preds)} predictions to {path}")


def _export_torch(trainer, mc, path: str) -> None:
    """Save ``trainer.state``'s params as a reference-compatible torch
    state_dict (main() restores the best checkpoint into the trainer
    before calling this)."""
    import jax
    import torch

    from gnot_tpu.interop.torch_oracle import flax_to_state_dict

    if jax.process_count() > 1:
        # Sharded params may span non-addressable devices; gather the
        # global values onto every host (collective — all processes
        # must call it), then only process 0 writes.
        params = trainer.gathered_standard_params()
        if jax.process_index() != 0:
            return
    else:
        params = jax.device_get(trainer.standard_params())
    torch.save(flax_to_state_dict(params, mc), path)
    print(f"Exported torch state_dict to {path}")


if __name__ == "__main__":
    main()
